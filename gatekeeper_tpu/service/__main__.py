"""CLI entry: python -m gatekeeper_tpu.service [--address A] [--driver D]"""

import argparse
import logging

from .server import serve


def main() -> None:
    p = argparse.ArgumentParser(description="gatekeeper_tpu policy service")
    p.add_argument("--address", default="127.0.0.1:50061",
                   help="bind address (host:port)")
    p.add_argument("--driver", default="tpu", choices=["tpu", "rego"],
                   help="evaluation backend")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    serve(address=args.address, driver=args.driver)


if __name__ == "__main__":
    main()
