"""Remote policy client: the Client surface over the gRPC service.

Mirrors gatekeeper_tpu.client.Client method-for-method so callers (and
the driver-agnostic conformance suite, tests/test_client.py) can swap a
local client for a remote one unchanged. Errors re-raise as the exact
ClientError subclass the server hit, reconstructed from the JSON detail
envelope (server.py)."""

from __future__ import annotations

import json
from typing import Any, Optional

import grpc

from ..client.types import (
    ClientError,
    MissingTemplateError,
    Response,
    Responses,
    Result,
    UnrecognizedConstraintError,
)
from ..target import AugmentedReview, AugmentedUnstructured
from .server import SERVICE_NAME, _dumps, _loads

_ERRORS = {
    "ClientError": ClientError,
    "MissingTemplateError": MissingTemplateError,
    "UnrecognizedConstraintError": UnrecognizedConstraintError,
}

# only these codes carry the server's JSON error envelope; anything else
# (UNAVAILABLE, DEADLINE_EXCEEDED, ...) is a transport problem and must
# NOT masquerade as a policy validation failure
_ENVELOPE_CODES = (grpc.StatusCode.INVALID_ARGUMENT,
                   grpc.StatusCode.INTERNAL)


class RemoteTransportError(Exception):
    """The RPC itself failed (server down, timeout, ...). Deliberately NOT
    a ClientError: callers treating ClientError as 'the request was
    rejected' must not mistake an outage for a validation verdict."""

    def __init__(self, code, details: str):
        super().__init__(f"{code.name}: {details}")
        self.code = code


def _raise_remote(e: grpc.RpcError):
    if e.code() not in _ENVELOPE_CODES:
        raise RemoteTransportError(e.code(), e.details() or "") from e
    detail = e.details() or ""
    try:
        env = json.loads(detail)
    except (ValueError, TypeError):
        raise ClientError(detail) from None
    cls = _ERRORS.get(env.get("error"))
    if cls is UnrecognizedConstraintError:
        raise UnrecognizedConstraintError(env.get("kind") or "?") from None
    if cls is not None:
        raise cls(env.get("message", detail)) from None
    raise ClientError(env.get("message", detail)) from None


def _result_from_wire(d: dict) -> Result:
    return Result(
        msg=d.get("msg", ""),
        metadata=d.get("metadata") or {},
        constraint=d.get("constraint"),
        review=d.get("review"),
        resource=d.get("resource"),
        enforcement_action=d.get("enforcementAction") or "deny",
    )


def _responses_from_wire(d: dict) -> Responses:
    out = Responses()
    for name, resp in (d.get("byTarget") or {}).items():
        out.by_target[name] = Response(
            target=resp.get("target") or name,
            trace=resp.get("trace"),
            input=resp.get("input"),
            results=[_result_from_wire(r)
                     for r in resp.get("results") or []],
        )
    out.handled = d.get("handled") or {}
    return out


def _review_to_wire(obj: Any) -> dict:
    if isinstance(obj, AugmentedReview):
        item: dict = {"admissionRequest": obj.admission_request}
        if obj.namespace is not None:
            item["namespace"] = obj.namespace
        return item
    if isinstance(obj, AugmentedUnstructured):
        item = {"object": obj.object}
        if obj.namespace is not None:
            item["namespace"] = obj.namespace
        return item
    if isinstance(obj, dict):
        # plain dicts go as "raw" so the SERVER's target handler applies
        # its own duck-typing — wire-side classification would diverge
        # from the local Client (e.g. an unhandleable dict must come back
        # unhandled, not wrapped into an AugmentedUnstructured)
        return {"raw": obj}
    raise ClientError(f"cannot send review of type {type(obj).__name__}")


class RemoteClient:
    """gRPC-backed drop-in for gatekeeper_tpu.client.Client."""

    def __init__(self, address: str,
                 channel: Optional[grpc.Channel] = None):
        self._channel = channel or grpc.insecure_channel(address)
        self._call = {}

    def close(self) -> None:
        self._channel.close()

    def _rpc(self, method: str, req: dict) -> dict:
        call = self._call.get(method)
        if call is None:
            call = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=_dumps,
                response_deserializer=_loads,
            )
            self._call[method] = call
        try:
            return call(req)
        except grpc.RpcError as e:
            _raise_remote(e)

    # ------------------------------------------------- lifecycle methods

    def add_template(self, template: dict) -> Responses:
        self._rpc("PutTemplate", {"template": template})
        return Responses()

    def remove_template(self, template: dict) -> Responses:
        self._rpc("RemoveTemplate", {"template": template})
        return Responses()

    def create_crd(self, template: dict) -> dict:
        return self._rpc("CreateCRD", {"template": template})["crd"]

    def add_constraint(self, constraint: dict) -> Responses:
        self._rpc("PutConstraint", {"constraint": constraint})
        return Responses()

    def remove_constraint(self, constraint: dict) -> Responses:
        self._rpc("RemoveConstraint", {"constraint": constraint})
        return Responses()

    def add_data(self, obj: Any) -> Responses:
        self._rpc("PutData", {"object": obj})
        return Responses()

    def remove_data(self, obj: Any) -> Responses:
        self._rpc("RemoveData", {"object": obj})
        return Responses()

    # ------------------------------------------------------- evaluation

    def review(self, obj: Any, tracing: bool = False) -> Responses:
        req = _review_to_wire(obj)
        if tracing:
            req["tracing"] = True
        return _responses_from_wire(self._rpc("Review", req))

    def review_batch(self, objs: list, tracing: bool = False
                     ) -> list[Responses]:
        req = {"reviews": [_review_to_wire(o) for o in objs]}
        if tracing:
            req["tracing"] = True
        return [_responses_from_wire(r)
                for r in self._rpc("ReviewBatch", req)["responses"]]

    def review_stream(self, batches, tracing: bool = False,
                      raw: bool = False):
        """STREAMING ingest: iterate over batches (each a list of
        review objects) and yield one list[Responses] per batch, in
        order, over a single pipelined HTTP/2 stream — no per-RPC
        round trip between batches. A per-batch server error raises
        the mapped ClientError for THAT batch when its result is
        consumed; the stream itself stays usable only up to the raise
        (iterate defensively for scan workloads).

        raw=True yields the wire response dicts untranslated (one
        list[dict] per batch, each `{"byTarget": ...}`): bulk scans
        flattening a million reviews to verdict pairs have no use for
        a million intermediate Result objects."""
        call = self._call.get("ReviewStream")
        if call is None:
            call = self._channel.stream_stream(
                f"/{SERVICE_NAME}/ReviewStream",
                request_serializer=_dumps,
                response_deserializer=_loads,
            )
            self._call["ReviewStream"] = call

        def requests():
            for objs in batches:
                req = {"reviews": [_review_to_wire(o) for o in objs]}
                if tracing:
                    req["tracing"] = True
                yield req

        try:
            for resp in call(requests()):
                err = resp.get("error")
                if err:
                    cls = _ERRORS.get(err.get("error"), ClientError)
                    if cls is UnrecognizedConstraintError:
                        raise cls(err.get("kind") or "?")
                    raise cls(err.get("message") or "stream batch failed")
                if raw:
                    yield resp.get("responses") or []
                else:
                    yield [_responses_from_wire(r)
                           for r in resp.get("responses") or []]
        except grpc.RpcError as e:
            _raise_remote(e)

    def audit(self, tracing: bool = False) -> Responses:
        req = {"tracing": True} if tracing else {}
        return _responses_from_wire(self._rpc("Audit", req))

    # ------------------------------------------------------------- misc

    def reset(self) -> None:
        self._rpc("Reset", {})

    def dump(self) -> str:
        return self._rpc("Dump", {})["dump"]

    def template_kinds(self) -> list[str]:
        return self._rpc("TemplateKinds", {})["kinds"]

    def knows_kind(self, kind: str) -> bool:
        return kind in self.template_kinds()
