"""Policy service: localhost gRPC batch engine + remote client.

`python -m gatekeeper_tpu.service` starts a resident engine serving the
Client surface (templates/constraints/data, batched Review, Audit) as
JSON-over-gRPC; RemoteClient is the drop-in counterpart. See
server.py for the wire contract and the rationale for JSON payloads.
"""

from .client import RemoteClient, RemoteTransportError
from .server import (
    INGEST_METHODS,
    SERVICE_NAME,
    PolicyService,
    make_server,
    serve,
)

__all__ = [
    "RemoteClient",
    "RemoteTransportError",
    "PolicyService",
    "SERVICE_NAME",
    "INGEST_METHODS",
    "make_server",
    "serve",
]
