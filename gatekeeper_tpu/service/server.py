"""gRPC batch policy service at the Client/Driver seam.

The communication backend of the framework (SURVEY.md §2.5): a resident
policy engine process serving template/constraint/data lifecycle plus
batched Review and Audit over localhost gRPC — the role the reference
embeds in its controller process behind the Driver interface
(vendor/github.com/open-policy-agent/frameworks/constraint/pkg/client/
drivers/interface.go:21-39).

Wire format: UTF-8 JSON request/response bodies over unary gRPC methods
(service `gatekeeper.v1.Policy`). JSON instead of protobuf is deliberate:
the payloads ARE Kubernetes unstructured objects (templates, constraints,
AdmissionReviews), which k8s itself serializes as JSON; no generated stubs
or .proto toolchain is needed, and the messages stay human-debuggable
(`grpcurl -plaintext -d '{...}'` works out of the box).

Errors cross the wire as INVALID_ARGUMENT with a JSON detail envelope
{"error": <exception class>, "message": ...} so the remote client
(service/client.py) can re-raise the exact ClientError subclass —
conformance-tested by running the driver-agnostic e2e suite
(tests/test_client.py) over a live localhost server.

Streaming ingest (ROADMAP item 5): `ReviewStream` is a bidirectional
stream of the same ReviewBatch wire messages — bulk callers (CI
scanners, service-mesh authorizers) keep ONE HTTP/2 stream open and
pipeline batch after batch without per-RPC setup, connection churn, or
HTTP/1.1 framing. Per-batch failures answer an {"error": ...} message
on the stream instead of aborting it, so one malformed batch cannot
kill a million-manifest scan.
"""

from __future__ import annotations

import json
import logging
import threading
from concurrent import futures
from typing import Any, Optional

import grpc

from ..client import Backend, Client, RegoDriver
from ..client.types import ClientError, Responses, Result
from ..control import jsonio
from ..ir import TpuDriver
from ..target import (
    AugmentedReview,
    AugmentedUnstructured,
    K8sValidationTarget,
)

log = logging.getLogger("gatekeeper_tpu.service")

SERVICE_NAME = "gatekeeper.v1.Policy"


# ------------------------------------------------------------------ codec
# jsonio rides orjson when the image carries it (~5x less codec CPU on
# the batched review path — the messages ARE the payload here) and
# degrades to the stdlib with identical wire bytes semantics


def _dumps(obj: Any) -> bytes:
    return jsonio.dumps_bytes(obj)


def _loads(data: bytes) -> Any:
    return jsonio.loads(data)


def result_to_wire(r: Result) -> dict:
    return {
        "msg": r.msg,
        "metadata": r.metadata,
        "constraint": r.constraint,
        "review": r.review,
        "resource": r.resource,
        "enforcementAction": r.enforcement_action,
    }


def responses_to_wire(resps: Responses) -> dict:
    return {
        "byTarget": {
            name: {
                "target": resp.target,
                "trace": resp.trace,
                "input": resp.input,
                "results": [result_to_wire(r) for r in resp.results],
            }
            for name, resp in resps.by_target.items()
        },
        "handled": resps.handled,
    }


def _wrap_review(item: dict) -> Any:
    """Reconstruct the review argument from its wire form:
    {"object": ...} | {"admissionRequest": ...} | {"raw": ...} (plain dict
    left to the target handler's own duck-typing), optional "namespace"."""
    ns = item.get("namespace")
    if "admissionRequest" in item:
        return AugmentedReview(admission_request=item["admissionRequest"],
                               namespace=ns)
    if "object" in item:
        return AugmentedUnstructured(object=item["object"], namespace=ns)
    if "raw" in item:
        return item["raw"]
    raise ClientError(
        "review item needs 'object', 'admissionRequest', or 'raw'")


# ---------------------------------------------------------------- service


class PolicyService:
    """Method handlers over one resident Client. Client methods already
    lock internally; handlers are therefore safe under gRPC's thread
    pool."""

    def __init__(self, client: Client):
        self.client = client

    # every handler: dict -> dict (JSON roundtrip handled by the codec)

    def put_template(self, req: dict) -> dict:
        self.client.add_template(req["template"])
        return {"ok": True}

    def remove_template(self, req: dict) -> dict:
        self.client.remove_template(req["template"])
        return {"ok": True}

    def create_crd(self, req: dict) -> dict:
        return {"crd": self.client.create_crd(req["template"])}

    def put_constraint(self, req: dict) -> dict:
        self.client.add_constraint(req["constraint"])
        return {"ok": True}

    def remove_constraint(self, req: dict) -> dict:
        self.client.remove_constraint(req["constraint"])
        return {"ok": True}

    def put_data(self, req: dict) -> dict:
        self.client.add_data(req["object"])
        return {"ok": True}

    def remove_data(self, req: dict) -> dict:
        self.client.remove_data(req["object"])
        return {"ok": True}

    def review(self, req: dict) -> dict:
        resps = self.client.review(_wrap_review(req),
                                   tracing=bool(req.get("tracing")))
        return responses_to_wire(resps)

    def review_batch(self, req: dict) -> dict:
        """Batched admission: one RPC, many reviews — the micro-batcher's
        wire form. Routes through Client.review_batch so the driver's
        vectorized evaluation amortizes the whole batch (per-item
        Client.review here forfeited the batching the RPC exists for)."""
        tracing = bool(req.get("tracing"))
        objs = [_wrap_review(item) for item in req.get("reviews", [])]
        resps = self.client.review_batch(objs, tracing=tracing)
        return {"responses": [responses_to_wire(r) for r in resps]}

    def review_stream(self, request_iterator, context):
        """Streaming ingest: each inbound message is one ReviewBatch
        request; each outbound message is its ReviewBatch response (or
        a per-batch {"error": ...} — the stream survives bad batches).
        Batches pipeline on one HTTP/2 stream: the caller needs no
        per-RPC round trip, and the engine sees back-to-back batches."""
        for req in request_iterator:
            try:
                yield self.review_batch(req)
            except ClientError as e:
                yield {"error": {"error": type(e).__name__,
                                 "message": str(e),
                                 "kind": getattr(e, "kind", None)}}
            except Exception as e:  # keep the stream alive; log it
                log.exception("internal error in ReviewStream batch")
                yield {"error": {"error": "InternalError",
                                 "message": str(e)}}

    def audit(self, req: dict) -> dict:
        return responses_to_wire(
            self.client.audit(tracing=bool(req.get("tracing"))))

    def reset(self, req: dict) -> dict:
        self.client.reset()
        return {"ok": True}

    def dump(self, req: dict) -> dict:
        return {"dump": self.client.dump()}

    def template_kinds(self, req: dict) -> dict:
        return {"kinds": self.client.template_kinds()}


_METHODS = {
    "PutTemplate": "put_template",
    "RemoveTemplate": "remove_template",
    "CreateCRD": "create_crd",
    "PutConstraint": "put_constraint",
    "RemoveConstraint": "remove_constraint",
    "PutData": "put_data",
    "RemoveData": "remove_data",
    "Review": "review",
    "ReviewBatch": "review_batch",
    "Audit": "audit",
    "Reset": "reset",
    "Dump": "dump",
    "TemplateKinds": "template_kinds",
}

# read-only evaluation surface for the Runtime's --ingest-grpc
# endpoint: bulk callers get Review/ReviewBatch/ReviewStream (and kind
# discovery), never the library lifecycle — an unauthenticated ingest
# port must not be able to rewrite the serving policy library
INGEST_METHODS = ("Review", "ReviewBatch", "ReviewStream",
                  "TemplateKinds")


def _make_handler(service: PolicyService, attr: str):
    method = getattr(service, attr)

    def handle(request: dict, context: grpc.ServicerContext) -> dict:
        try:
            return method(request)
        except ClientError as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                json.dumps({"error": type(e).__name__, "message": str(e),
                            "kind": getattr(e, "kind", None)}))
        except Exception as e:  # internal: never leak a stack over the wire
            log.exception("internal error in %s", attr)
            context.abort(grpc.StatusCode.INTERNAL,
                          json.dumps({"error": "InternalError",
                                      "message": str(e)}))

    return grpc.unary_unary_rpc_method_handler(
        handle, request_deserializer=_loads, response_serializer=_dumps)


def make_server(client: Optional[Client] = None, address: str = "127.0.0.1:0",
                driver: str = "tpu", max_workers: int = 8,
                expose: Optional[tuple] = None):
    """-> (grpc.Server, bound_port). Caller starts/stops the server.
    `expose` restricts the served method set (e.g. INGEST_METHODS for
    the Runtime's evaluation-only bulk ingest port)."""
    if client is None:
        drv = TpuDriver() if driver == "tpu" else RegoDriver()
        client = Backend(drv).new_client([K8sValidationTarget()])
    service = PolicyService(client)
    handlers = {name: _make_handler(service, attr)
                for name, attr in _METHODS.items()
                if expose is None or name in expose}
    if expose is None or "ReviewStream" in expose:
        handlers["ReviewStream"] = grpc.stream_stream_rpc_method_handler(
            service.review_stream,
            request_deserializer=_loads, response_serializer=_dumps)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        # no SO_REUSEPORT: two engines silently sharing a port would split
        # traffic unpredictably; a second bind must FAIL (checked below)
        options=(("grpc.so_reuseport", 0),))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
    port = server.add_insecure_port(address)
    if port == 0:
        # grpc signals bind failure by returning port 0; serving anyway
        # would block forever on an address nobody reaches
        raise OSError(f"could not bind policy service to {address}")
    return server, port


def serve(address: str = "127.0.0.1:50061", driver: str = "tpu") -> None:
    """Blocking entry point (`python -m gatekeeper_tpu.service`)."""
    server, port = make_server(address=address, driver=driver)
    server.start()
    log.info("policy service listening on port %d (driver=%s)", port, driver)
    stop = threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        server.stop(grace=2.0)
