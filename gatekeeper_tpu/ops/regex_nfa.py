"""Device regex: byte-NFA bitmask scan over the packed vocab.

Match-table rows for `re_match` are normally built host-side, one
`re.search` per (pattern, vocab string) — O(vocab × patterns) Python work
that lands exactly where BASELINE config #3 hurts (high-cardinality
vocabularies under the regex-heavy pod-security-policy set). This module
compiles a practical regex subset to a ≤32-state Thompson NFA whose
subset-simulation is a pure bitmask program:

    state'[v] = float_start | OR_s∈state[v] trans[s, byte[v, t]]

i.e. per scan step one 256-entry gather and a handful of uint32 ops per
string — embarrassingly parallel over the vocab, so the whole pattern set
scans in a single fused device dispatch over StringTable.bytes_tensor
(replacing vendor/.../opa/topdown/regex.go's per-eval re_match with
precomputed tables, like every other string predicate here).

Python-`re.search` parity (unanchored search, ^/$ anchors, classes,
quantifiers, alternation) is differentially tested in
tests/test_regex_nfa.py; patterns outside the subset (or needing >32
states) raise Unsupported and keep the host path. `scan_vocab` picks
device vs host by workload size (DEVICE_CROSSOVER)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

MAX_STATES = 30  # CORE states (those with byte moves) per uint32 mask;
# bits 30/31 carry the accept / accept-at-end flags of a state SET, so a
# mask is a closed eps-set projected onto core states + its accept info
ACCEPT_BIT = np.uint32(1 << 30)
ACCEPT_END_BIT = np.uint32(1 << 31)
CORE_MASK = np.uint32((1 << 30) - 1)
MAX_LEN = 128  # must match StringTable.bytes_tensor default

# minimum (new strings x regex rows) before a device dispatch beats the
# host loop. Measured: host re.search sustains ~2M (pattern, string)
# evals/s; one DFA-scan dispatch costs ~1s of fixed latency through a
# network-tunneled chip (microseconds locally) and then scales ~free in
# rows. The conservative figure below is the tunnel's break-even; local
# deployments can lower it via this module attribute.
DEVICE_CROSSOVER = 4_000_000


class Unsupported(Exception):
    pass


# ------------------------------------------------------------ pattern AST


@dataclass
class _Node:
    kind: str  # lit | any | class | cat | alt | star | plus | opt | caret | dollar | empty
    bytes_: Optional[bytes] = None  # allowed bytes for lit/class/any
    kids: tuple = ()


def _parse(pattern: str) -> _Node:
    """Recursive-descent parser for the supported subset."""
    pos = [0]
    p = pattern

    def peek() -> str:
        return p[pos[0]] if pos[0] < len(p) else ""

    def take() -> str:
        c = peek()
        pos[0] += 1
        return c

    def parse_alt() -> _Node:
        branches = [parse_cat()]
        while peek() == "|":
            take()
            branches.append(parse_cat())
        if len(branches) == 1:
            return branches[0]
        return _Node("alt", kids=tuple(branches))

    def parse_cat() -> _Node:
        items = []
        while peek() not in ("", "|", ")"):
            items.append(parse_repeat())
        if not items:
            return _Node("empty")
        if len(items) == 1:
            return items[0]
        return _Node("cat", kids=tuple(items))

    def parse_repeat() -> _Node:
        atom = parse_atom()
        while peek() in ("*", "+", "?"):
            op = take()
            if atom.kind in ("caret", "dollar"):
                raise Unsupported("quantified anchor")
            kind = {"*": "star", "+": "plus", "?": "opt"}[op]
            atom = _Node(kind, kids=(atom,))
        if peek() == "{":
            raise Unsupported("counted repetition")
        return atom

    def parse_atom() -> _Node:
        c = take()
        if c == "^":
            return _Node("caret")
        if c == "$":
            return _Node("dollar")
        if c == ".":
            return _Node("any", bytes_=bytes(range(1, 256)))
        if c == "(":
            if peek() == "?":
                raise Unsupported("group flags")
            inner = parse_alt()
            if take() != ")":
                raise Unsupported("unbalanced group")
            return inner
        if c == "[":
            return parse_class()
        if c == "\\":
            return _Node("lit", bytes_=escape_bytes(take()))
        if c in ")*+?":
            raise Unsupported(f"dangling {c!r}")
        b = c.encode("utf-8")
        if len(b) != 1:
            raise Unsupported("non-ascii literal")
        return _Node("lit", bytes_=b)

    def escape_bytes(c: str) -> bytes:
        if c == "":
            raise Unsupported("trailing backslash")
        if c == "d":
            return bytes(range(ord("0"), ord("9") + 1))
        if c == "w":
            return (bytes(range(ord("a"), ord("z") + 1)) +
                    bytes(range(ord("A"), ord("Z") + 1)) +
                    bytes(range(ord("0"), ord("9") + 1)) + b"_")
        if c == "s":
            return b" \t\r\n\f\v"
        if c in ".^$*+?()[]{}|\\/-":
            return c.encode()
        raise Unsupported(f"escape \\{c}")

    def parse_class() -> _Node:
        negate = peek() == "^"
        if negate:
            take()
        members = bytearray()
        first = True
        while True:
            c = take()
            if c == "":
                raise Unsupported("unterminated class")
            if c == "]" and not first:
                break
            first = False
            if c == "\\":
                members.extend(escape_bytes(take()))
                continue
            b = c.encode("utf-8")
            if len(b) != 1:
                raise Unsupported("non-ascii class member")
            if peek() == "-" and pos[0] + 1 < len(p) and p[pos[0] + 1] != "]":
                take()
                hi = take()
                hb = hi.encode("utf-8")
                if hi == "\\":
                    hb = escape_bytes(take())
                    if len(hb) != 1:
                        raise Unsupported("range over class escape")
                if len(hb) != 1 or hb[0] < b[0]:
                    raise Unsupported("bad class range")
                members.extend(range(b[0], hb[0] + 1))
            else:
                members.extend(b)
        allowed = set(members)
        if negate:
            allowed = set(range(1, 256)) - allowed
        if not allowed:
            raise Unsupported("empty class")
        return _Node("class", bytes_=bytes(sorted(allowed)))

    node = parse_alt()
    if pos[0] != len(p):
        raise Unsupported(f"unparsed tail {p[pos[0]:]!r}")
    return node


# -------------------------------------------------------- NFA construction


class _Builder:
    """Thompson construction. Edge kinds: eps, caret (eps valid only at
    position 0), dollar (eps valid only at end of string), byte sets."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.caret: list[list[int]] = []
        self.dollar: list[list[int]] = []
        self.moves: list[list[tuple[bytes, int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.caret.append([])
        self.dollar.append([])
        self.moves.append([])
        return len(self.eps) - 1

    def build(self, node: _Node) -> tuple[int, int]:
        """-> (entry, exit) state pair for the fragment."""
        k = node.kind
        if k == "empty":
            s = self.new_state()
            return s, s
        if k in ("lit", "any", "class"):
            a, b = self.new_state(), self.new_state()
            self.moves[a].append((node.bytes_, b))
            return a, b
        if k == "caret":
            a, b = self.new_state(), self.new_state()
            self.caret[a].append(b)
            return a, b
        if k == "dollar":
            a, b = self.new_state(), self.new_state()
            self.dollar[a].append(b)
            return a, b
        if k == "cat":
            first, last = None, None
            for kid in node.kids:
                a, b = self.build(kid)
                if first is None:
                    first = a
                else:
                    self.eps[last].append(a)
                last = b
            return first, last
        if k == "alt":
            a, b = self.new_state(), self.new_state()
            for kid in node.kids:
                ka, kb = self.build(kid)
                self.eps[a].append(ka)
                self.eps[kb].append(b)
            return a, b
        if k in ("star", "plus", "opt"):
            ka, kb = self.build(node.kids[0])
            a, b = self.new_state(), self.new_state()
            self.eps[a].append(ka)
            if k != "plus":
                self.eps[a].append(b)
            self.eps[kb].append(b)
            if k != "opt":
                self.eps[kb].append(ka)
            return a, b
        raise Unsupported(f"node {k}")


@dataclass
class NfaProgram:
    """Bitmask NFA, ready for vectorized subset simulation.

    A mask encodes an eps-CLOSED state set projected onto core states
    (states with outgoing byte moves), plus two flag bits: ACCEPT_BIT
    (the set contains accept) and ACCEPT_END_BIT (the set reaches accept
    once $-edges open up at end of string).

    table[c, byte]  — mask reachable from core state c on byte
    start0          — start-set mask at position 0 (follows ^ edges)
    float_start     — start-set mask injected at every position (search)
    """

    n_core: int
    table: np.ndarray  # [S, 256] uint32
    start0: int
    float_start: int

    def match_host(self, s: str) -> bool:
        """Host reference simulation (used for tests and tiny batches)."""
        bs = s.encode("utf-8")[:MAX_LEN]
        state = self.start0
        if state & int(ACCEPT_BIT):
            return True
        if not bs and state & int(ACCEPT_END_BIT):
            return True
        for t, byte in enumerate(bs):
            nxt = 0
            st = state & int(CORE_MASK)
            while st:
                low = st & -st
                nxt |= int(self.table[low.bit_length() - 1, byte])
                st ^= low
            state = nxt | self.float_start
            if state & int(ACCEPT_BIT):
                return True
            if t + 1 == len(bs) and state & int(ACCEPT_END_BIT):
                return True
        return False


def compile_pattern(pattern: str) -> NfaProgram:
    """pattern -> bitmask NFA with Python re.search semantics, or raises
    Unsupported (host fallback)."""
    node = _parse(pattern)
    b = _Builder()
    entry, exit_ = b.build(node)
    n = len(b.eps)

    core = [s for s in range(n) if b.moves[s]]
    if len(core) > MAX_STATES:
        raise Unsupported(f"{len(core)} core states > {MAX_STATES}")
    core_bit = {s: i for i, s in enumerate(core)}

    def closure(seed: set[int], caret: bool, dollar: bool) -> set[int]:
        out = set(seed)
        work = list(seed)
        while work:
            s = work.pop()
            nxts = list(b.eps[s])
            if caret:
                nxts += b.caret[s]
            if dollar:
                nxts += b.dollar[s]
            for t in nxts:
                if t not in out:
                    out.add(t)
                    work.append(t)
        return out

    def mask_of(seed: set[int], caret: bool = False) -> int:
        """Closed set -> core projection + accept flags."""
        closed = closure(seed, caret=caret, dollar=False)
        m = 0
        for s in closed:
            bit = core_bit.get(s)
            if bit is not None:
                m |= 1 << bit
        if exit_ in closed:
            m |= int(ACCEPT_BIT)
        if exit_ in closure(closed, caret=False, dollar=True):
            m |= int(ACCEPT_END_BIT)
        return m

    table = np.zeros((max(1, len(core)), 256), dtype=np.uint32)
    for s in core:
        for allowed, target in b.moves[s]:
            tmask = np.uint32(mask_of({target}))
            arr = np.frombuffer(allowed, dtype=np.uint8)
            table[core_bit[s], arr] |= tmask
    return NfaProgram(
        n_core=len(core),
        table=table,
        start0=mask_of({entry}, caret=True),
        float_start=mask_of({entry}, caret=False),
    )


# ----------------------------------------------- DFA (the device program)


@dataclass
class DfaProgram:
    """Subset-constructed DFA of the search-NFA, with an ABSORBING match
    sink (any set containing accept collapses into it), so the device
    step is ONE gather per byte and acceptance is a final-state check.
    accept_end[s] flags sets that accept once $-edges open at the
    string's end (the scan freezes each string's state at its last real
    byte, so the final state IS the end-of-string state)."""

    table: np.ndarray  # [S, 256] int32 next-state ids
    accept_end: np.ndarray  # [S] bool
    start: int
    matched: int


MAX_DFA_STATES = 512


def compile_dfa(prog: NfaProgram,
                max_states: int = MAX_DFA_STATES) -> DfaProgram:
    """NfaProgram -> DfaProgram, or Unsupported on state blowup."""
    CORE = int(CORE_MASK)
    ACC = int(ACCEPT_BIT)
    floatm = prog.float_start

    def step(mask: int, byte: int) -> int:
        nxt = 0
        st = mask & CORE
        while st:
            low = st & -st
            nxt |= int(prog.table[low.bit_length() - 1, byte])
            st ^= low
        return nxt | floatm

    ids: dict[int, int] = {}
    rows: list[np.ndarray] = []
    ends: list[bool] = []

    MATCHED = 0  # reserve id 0 for the absorbing sink
    rows.append(np.zeros(256, dtype=np.int32))  # self-loops
    ends.append(True)

    def intern_mask(mask: int) -> int:
        if mask & ACC:
            return MATCHED
        i = ids.get(mask)
        if i is None:
            if len(rows) >= max_states:
                raise Unsupported("DFA state blowup")
            i = len(rows)
            ids[mask] = i
            rows.append(np.zeros(256, dtype=np.int32))
            ends.append(bool(mask & int(ACCEPT_END_BIT)))
            work.append((i, mask))
        return i

    work: list[tuple[int, int]] = []
    start = intern_mask(prog.start0)
    while work:
        i, mask = work.pop()
        row = rows[i]
        for byte in range(1, 256):
            row[byte] = intern_mask(step(mask, byte))
    return DfaProgram(
        table=np.stack(rows),
        accept_end=np.asarray(ends, dtype=bool),
        start=start,
        matched=MATCHED,
    )


# ------------------------------------------------------------- device scan


_scan_cache: dict = {}


def _pad_len(n: int) -> int:
    """Bucket scan length to limit jit variants."""
    out = 16
    while out < n:
        out *= 2
    return min(out, MAX_LEN)


def scan_device(dfas: list[DfaProgram], bytes_mat: np.ndarray) -> np.ndarray:
    """-> matched[P, V] bool: every pattern against every vocab string in
    one device dispatch. Per scan step the whole [P, V] state sheet takes
    ONE flat gather into the stacked DFA tables; each string's state
    freezes at its last real byte, so '$' acceptance reads off the final
    state. Strings must be NUL-free (byte 0 is the pad terminator)."""
    import jax
    import jax.numpy as jnp

    P = len(dfas)
    s_max = max(d.table.shape[0] for d in dfas)
    table = np.zeros((P, s_max, 256), dtype=np.int32)
    accept_end = np.zeros((P, s_max), dtype=bool)
    start = np.zeros(P, dtype=np.int32)
    matched_id = np.zeros(P, dtype=np.int32)
    for i, d in enumerate(dfas):
        table[i, : d.table.shape[0]] = d.table
        accept_end[i, : d.table.shape[0]] = d.accept_end
        start[i] = d.start
        matched_id[i] = d.matched

    # trim the scan to the longest real string (bucketed)
    real_len = int((bytes_mat != 0).sum(axis=1).max()) if len(bytes_mat) \
        else 0
    L = _pad_len(max(real_len, 1))
    bmat = np.ascontiguousarray(bytes_mat[:, :L])

    key = (s_max, L, bmat.shape[0], P)
    fn = _scan_cache.get(key)
    if fn is None:
        def run(table, accept_end, start, matched_id, bmat):
            V = bmat.shape[0]
            flat = table.reshape(-1)  # [(P*S)*256]
            p_base = (jnp.arange(P, dtype=jnp.int32) * s_max)[:, None]
            state0 = jnp.broadcast_to(start[:, None], (P, V))

            def body(state, t):
                byte = bmat[:, t]  # [V]
                idx = (p_base + state) * 256 + byte[None, :]
                nxt = flat[idx]
                # byte 0 = past end of string: freeze the state there
                return jnp.where((byte != 0)[None, :], nxt, state), None

            state, _ = jax.lax.scan(body, state0, jnp.arange(L))
            matched = state == matched_id[:, None]
            matched |= accept_end.reshape(-1)[p_base + state]
            return matched

        fn = jax.jit(run)
        _scan_cache[key] = fn
    out = fn(table, accept_end, start, matched_id, bmat)
    return np.asarray(out)


def bytes_matrix(strings: list[str]) -> np.ndarray:
    """[V, MAX_LEN] uint8, zero-padded (StringTable.bytes_tensor shape)."""
    out = np.zeros((len(strings), MAX_LEN), dtype=np.uint8)
    for i, s in enumerate(strings):
        bs = s.encode("utf-8")[:MAX_LEN]
        out[i, : len(bs)] = np.frombuffer(bs, dtype=np.uint8)
    return out


def try_compile(pattern: str) -> Optional[NfaProgram]:
    try:
        return compile_pattern(pattern)
    except Unsupported:
        return None


def try_compile_device(pattern: str) -> Optional[DfaProgram]:
    try:
        return compile_dfa(compile_pattern(pattern))
    except Unsupported:
        return None


def strings_scannable(strings: list[str]) -> bool:
    """True when every string round-trips faithfully through the byte
    matrix: fits MAX_LEN, pure ASCII (byte-wise '.'/negated-class
    semantics diverge from re's per-char semantics past that), no NUL
    (the scan's end-of-string terminator), and no newline (re gives '.'
    and '$' special newline behavior the byte NFA does not model)."""
    for s in strings:
        b = s.encode("utf-8")
        if len(b) > MAX_LEN or max(b, default=0) > 127:
            return False
        if 0 in b or 0x0A in b:
            return False
    return True


def scan_vocab(patterns: list[str], strings: list[str],
               bytes_mat: Optional[np.ndarray] = None,
               force_device: Optional[bool] = None) -> Optional[np.ndarray]:
    """-> matched[len(patterns), len(strings)] bool, or None when any
    pattern is outside the NFA subset (caller keeps its host path).
    Device vs host is chosen by workload size unless force_device set."""
    try:
        progs = [compile_pattern(p) for p in patterns]
        dfas = [compile_dfa(p) for p in progs]
    except Unsupported:
        return None
    if not strings_scannable(strings):
        return None
    use_device = (len(patterns) * len(strings) >= DEVICE_CROSSOVER
                  if force_device is None else force_device)
    if use_device:
        return scan_device(dfas, bytes_mat if bytes_mat is not None
                           else bytes_matrix(strings))
    out = np.zeros((len(patterns), len(strings)), dtype=bool)
    for i, prog in enumerate(progs):
        out[i] = [prog.match_host(s) for s in strings]
    return out
