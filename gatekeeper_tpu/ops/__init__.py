from .strtab import MatchTables, StringTable

__all__ = ["MatchTables", "StringTable"]
