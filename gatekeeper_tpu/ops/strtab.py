"""String intern table + pattern match tables.

The vectorized evaluator never touches raw strings on device: every string
in objects, parameters, and templates is interned to an int32 id, and
string predicates (startswith/endswith/contains/re_match/equality against
patterns) become boolean lookup tables `table[pattern_row, string_id]`
computed once per (pattern set, vocab epoch) and gathered on device.

This mirrors how the reference's hot loop spends its time — the OPA
topdown evaluator re-running string builtins per object per constraint
(vendor/.../opa/topdown, e.g. re_match at topdown/regex.go) — except the
work is hoisted out of the cross-product entirely: string predicates cost
O(vocab × patterns) once, then O(1) gathers inside the [objects ×
constraints] sweep.

Tables are built host-side with numpy here; ops/regex_nfa.py provides the
device path (byte-NFA bitmask scan over the packed vocab bytes) used when
the vocab is large enough to matter.
"""

from __future__ import annotations

import re
from typing import Any, Iterable

import numpy as np

PAD_ID = 0  # id 0 is reserved: "absent"; real strings start at 1


def escape_transform_arg(arg: str) -> str:
    """Escape a pattern-transform argument for embedding in an
    "<op>@<tag>:<arg>" op string ("@" delimits tags)."""
    return arg.replace("%", "%25").replace("@", "%40")


def _unescape_transform_arg(arg: str) -> str:
    return arg.replace("%40", "@").replace("%25", "%")


def canon_num(v) -> str:
    """Canonical string form of a number, interned so numeric equality on
    device is exact (f32 cells are approximate past 2^24)."""
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return "\x01n" + str(int(f))
    return "\x01n" + repr(f)


def vocab_cap(v: int) -> int:
    """Capacity bucket for vocab-indexed device arrays: padding to a
    power of two keeps their SHAPES stable while the vocab grows, so a
    single new interned string does not recompile every jitted sweep
    (XLA kernels are shape-specialized)."""
    c = 256
    while c < v:
        c *= 2
    return c


class StringTable:
    """Append-only intern table. Ids are stable for the life of the table;
    `epoch` increments on growth so cached match tables know to extend."""

    def __init__(self):
        self._ids: dict[str, int] = {}
        self._strs: list[str] = ["\x00<pad>"]  # id 0 placeholder
        self.epoch = 0

    def __len__(self) -> int:
        return len(self._strs)

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
            self.epoch += 1
        return i

    def intern_many(self, ss: Iterable[str]) -> list[int]:
        return [self.intern(s) for s in ss]

    def lookup(self, s: str) -> int:
        """Id of s, or PAD_ID if never interned (≠ any real string)."""
        return self._ids.get(s, PAD_ID)

    def snapshot(self) -> tuple[int, int]:
        """(size, epoch) marker. Interning is append-only — ids handed
        out before a snapshot are NEVER reassigned — so caches holding
        encoded rows stay valid across vocab growth and only need to
        extend vocab-indexed tables past the snapshot size (the
        incremental audit patches dirty rows against exactly this
        invariant)."""
        return (len(self._strs), self.epoch)

    def grown_since(self, snap: tuple[int, int]) -> int:
        """How many strings were interned after `snap` was taken (the
        per-sweep vocab-growth signal the audit metrics report)."""
        return len(self._strs) - snap[0]

    def string(self, i: int) -> str:
        return self._strs[i]

    def dump(self) -> list[str]:
        """All interned strings in id order (excluding the pad entry) —
        the warm-restart vocab snapshot. Restoring this list on a fresh
        table reproduces the exact id assignment, so persisted encoded
        rows (which hold int32 ids) and vocab-capacity-bucketed program
        shapes stay valid across process restarts."""
        return list(self._strs[1:])

    def restore(self, strings: Iterable[str]) -> None:
        """Re-intern a dump() onto a FRESH table. Refuses on a table
        that already interned anything: ids are append-only and already
        handed out, so replaying an old vocab underneath them would
        silently remap every existing id."""
        if len(self._strs) != 1:
            raise ValueError("vocab restore requires a fresh StringTable")
        for s in strings:
            if not isinstance(s, str):
                raise ValueError("vocab snapshot entries must be strings")
            self.intern(s)

    def bytes_tensor(self, max_len: int = 128) -> np.ndarray:
        """[V, max_len] uint8, zero-padded — the device-side vocab for
        NFA scans (ops/regex_nfa.py)."""
        out = np.zeros((len(self._strs), max_len), dtype=np.uint8)
        for i, s in enumerate(self._strs):
            if i == 0:
                continue
            b = s.encode("utf-8")[:max_len]
            out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        return out


class MatchTables:
    """Cache of boolean match vectors over the vocab, one row per
    (op, pattern) pair. Rows extend lazily as the vocab grows.

    Large regex extensions (many re_match rows × many new vocab strings —
    BASELINE config #3's shape) are batched through the device byte-NFA
    scan (ops/regex_nfa.py) in ONE dispatch; everything else, and any
    pattern outside the NFA subset, keeps the host re.search path."""

    # pattern-side transforms: "<op>@trim:<cutset>" applies the transform
    # to the pattern string at row-creation time (rego trim/trim_prefix/…
    # wrapped around a parameter pattern, e.g. forbidden-sysctls). Args are
    # %-escaped (see escape_transform_arg) so cutsets containing "@" can't
    # corrupt the tag encoding. Rego trim(s, "") strips nothing, so an
    # empty cutset is the identity (not Python's whitespace strip).
    TRANSFORMS = {
        "trim": lambda v, arg: v.strip(arg) if arg else v,
        "lower": lambda v, arg: v.lower(),
        "upper": lambda v, arg: v.upper(),
        "trim_prefix": lambda v, arg: v[len(arg):]
        if arg and v.startswith(arg) else v,
        "trim_suffix": lambda v, arg: v[: -len(arg)]
        if arg and v.endswith(arg) else v,
    }

    def __init__(self, table: StringTable):
        self.table = table
        self._rows: dict[tuple[str, str], int] = {}
        self._patterns: list[tuple[str, str]] = []
        self._data: list[np.ndarray] = []  # per row, bool[V_at_build]
        self._built_len: list[int] = []
        self._packed_cache: np.ndarray | None = None
        self._packed_key: tuple | None = None
        self._custom: dict[str, Any] = {}  # op -> fn(pattern, strings)->bool[]
        # per-materialize window caches: (built, V) -> decoded string
        # list / fixed-width unicode array. Without these, every row
        # re-decodes the same vocab window (O(rows × vocab) Python), and
        # the string-family ops loop per string; with them, decoding is
        # amortized across rows and startswith/endswith/contains/eq run
        # as numpy C loops over the whole window at once
        self._win_strs: dict[tuple[int, int], list] = {}
        self._win_arr: dict[tuple[int, int], np.ndarray] = {}

    def register_op(self, op: str, fn) -> None:
        """Custom predicate op (interpreter-backed binary helpers,
        ops/derived.py interp_pred). Idempotent per op name."""
        self._custom.setdefault(op, fn)

    def row(self, op: str, pattern: str) -> int:
        """Row index for (op, pattern); builds the vector on first use.
        op may carry @transform tags applied to the pattern here, so
        transformed patterns share rows with directly-written ones."""
        if "@" in op:
            op, _, tags = op.partition("@")
            for tag in tags.split("@"):
                name, _, arg = tag.partition(":")
                fn = self.TRANSFORMS.get(name)
                if fn is None:
                    raise ValueError(f"unknown pattern transform {name!r}")
                if isinstance(pattern, str):
                    pattern = fn(pattern, _unescape_transform_arg(arg))
        key = (op, pattern)
        r = self._rows.get(key)
        if r is None:
            r = len(self._patterns)
            self._rows[key] = r
            self._patterns.append(key)
            self._data.append(np.zeros(0, dtype=bool))
            self._built_len.append(0)
        return r

    def _extend_regex_rows_batched(self, V: int) -> None:
        """Fill pending re_match row extensions through the device NFA
        scan when the (rows × new strings) workload justifies a dispatch.
        Rows left untouched fall through to the host path in
        materialize()'s per-row loop."""
        groups: dict[int, list[int]] = {}
        for r, (op, pattern) in enumerate(self._patterns):
            if op in ("re_match", "glob") and isinstance(pattern, str) \
                    and self._built_len[r] < V:
                groups.setdefault(self._built_len[r], []).append(r)
        if not groups:
            return
        from . import regex_nfa

        for built, rows in groups.items():
            n_new = V - built
            if n_new * len(rows) < regex_nfa.DEVICE_CROSSOVER:
                continue
            progs = []
            prog_rows = []
            for r in rows:
                op, pattern = self._patterns[r]
                # glob rows ride the same device scan as regex rows via
                # their anchored-regex translation
                rx = self.glob_regex(pattern) if op == "glob" else pattern
                prog = regex_nfa.try_compile_device(rx)
                if prog is not None:
                    progs.append(prog)
                    prog_rows.append(r)
            if n_new * len(prog_rows) < regex_nfa.DEVICE_CROSSOVER:
                continue
            strings = self._window(built, V)
            # strings the byte matrix can't represent faithfully (NUL
            # markers like the pad entry / canon-num prefix are fine to
            # blank here and fix below; oversize or non-ascii strings
            # veto the whole batch)
            special_set = {k for k, s in enumerate(strings)
                           if "\x00" in s or "\x01" in s or "\n" in s}
            special = sorted(special_set)
            clean = ["" if k in special_set else s
                     for k, s in enumerate(strings)]
            if not regex_nfa.strings_scannable(clean):
                continue
            res = regex_nfa.scan_device(progs, regex_nfa.bytes_matrix(clean))
            for j, r in enumerate(prog_rows):
                row = np.array(res[j])  # jax outputs are read-only
                op, pattern = self._patterns[r]
                rx = self.glob_regex(pattern) if op == "glob" else pattern
                for k in special:
                    row[k] = re.search(rx, strings[k]) is not None
                if built == 0:
                    row[0] = False  # pad entry never matches
                self._data[r] = np.concatenate([self._data[r], row])
                self._built_len[r] = V

    def _window(self, built: int, V: int) -> list:
        """Decoded vocab strings [built, V), shared across rows."""
        key = (built, V)
        win = self._win_strs.get(key)
        if win is None:
            if len(self._win_strs) > 8:  # windows die with their epoch
                self._win_strs.clear()
                self._win_arr.clear()
            win = [self.table.string(i) for i in range(built, V)]
            self._win_strs[key] = win
        return win

    # fixed-width unicode arrays cost O(window × max_len); past this
    # length the vectorization win can't pay for the padding memory
    MAX_VECTOR_STRLEN = 512

    def _window_arr(self, built: int, V: int, strings: list[str]):
        """Fixed-width unicode array of the window, for the vectorized
        string-family ops (np.char runs the comparison as one C loop
        instead of a Python generator per row). None when an oversize
        string makes the padded array a bad trade — callers then keep
        the per-string host path."""
        key = (built, V)
        if key in self._win_arr:
            return self._win_arr[key]
        arr = None
        if strings:
            if max(len(s) for s in strings) <= self.MAX_VECTOR_STRLEN:
                arr = np.array(strings, dtype=str)
        else:
            arr = np.zeros(0, dtype="U1")
        self._win_arr[key] = arr
        return arr

    @staticmethod
    def glob_regex(pattern: str) -> str:
        """Image-ref style glob ('*' wildcard only) as an anchored
        regex — the single source of truth for both the host path and
        the device NFA batch."""
        return ("^" + ".*".join(re.escape(p) for p in pattern.split("*"))
                + "$")

    def _eval(self, op: str, pattern: str, strings: list[str],
              arr: np.ndarray | None = None) -> np.ndarray:
        if op in self._custom:
            return np.asarray(self._custom[op](pattern, strings), dtype=bool)
        if op in ("startswith", "endswith", "contains", "eq") and \
                arr is not None:
            if op == "startswith":
                return np.char.startswith(arr, pattern)
            if op == "endswith":
                return np.char.endswith(arr, pattern)
            if op == "contains":
                return np.char.find(arr, pattern) >= 0 if pattern else \
                    np.ones(len(strings), dtype=bool)
            return arr == pattern
        if op == "startswith":
            return np.fromiter((s.startswith(pattern) for s in strings),
                               dtype=bool, count=len(strings))
        if op == "endswith":
            return np.fromiter((s.endswith(pattern) for s in strings),
                               dtype=bool, count=len(strings))
        if op == "contains":
            return np.fromiter((pattern in s for s in strings),
                               dtype=bool, count=len(strings))
        if op == "eq":
            return np.fromiter((s == pattern for s in strings),
                               dtype=bool, count=len(strings))
        if op == "re_match":
            try:
                rx = re.compile(pattern)
            except re.error:
                return np.zeros(len(strings), dtype=bool)
            return np.fromiter((rx.search(s) is not None for s in strings),
                               dtype=bool, count=len(strings))
        if op == "glob":
            rx = re.compile(self.glob_regex(pattern))
            return np.fromiter((rx.search(s) is not None for s in strings),
                               dtype=bool, count=len(strings))
        raise ValueError(f"unknown match op {op!r}")

    def materialize_packed(self) -> np.ndarray:
        """[V, W] uint32 — bit r of word w set iff pattern row (w*32+r)
        matches the string. The device predicate is then a single fused
        int32 AND against a per-row bitmask (no extra broadcast dim).

        Cached until the vocab or pattern set grows, so steady-state audits
        reuse the same ndarray (and JAX skips re-uploading the buffer)."""
        key = (self.table.epoch, len(self._patterns))
        if self._packed_cache is not None and self._packed_key == key:
            return self._packed_cache
        table = self.materialize()  # [R, V]
        R, V = table.shape
        W = max(1, (R + 31) // 32)
        cap = vocab_cap(V)  # stable shape under vocab growth
        bits = np.zeros((cap, W * 32), dtype=bool)
        bits[:V, :R] = table.T
        weights = (1 << np.arange(32, dtype=np.uint64)).astype(np.uint64)
        words = (bits.reshape(cap, W, 32).astype(np.uint64) * weights).sum(
            axis=-1).astype(np.uint32)
        self._packed_cache = words
        self._packed_key = key
        return words

    def materialize(self) -> np.ndarray:
        """[R, V] bool — all rows, padded/extended to the current vocab.

        OPA semantics note: re_match is anchored like Go's regexp.MatchString
        (unanchored search), mirrored by using re.search above.
        """
        V = len(self.table)
        R = max(1, len(self._patterns))
        self._extend_regex_rows_batched(V)
        out = np.zeros((R, V), dtype=bool)
        for r, (op, pattern) in enumerate(self._patterns):
            built = self._built_len[r]
            if built < V:
                strings = self._window(built, V)
                arr = self._window_arr(built, V, strings)
                new = self._eval(op, pattern, strings, arr=arr)
                if built == 0:
                    # row 0 of the vocab is the pad entry: never matches
                    new[0] = False
                self._data[r] = np.concatenate([self._data[r], new])
                self._built_len[r] = V
            out[r, : self._built_len[r]] = self._data[r]
        return out
