"""Derived vocab columns: pure unary functions as device lookup tables.

The TPU evaluator never runs scalar string/number functions per (object,
constraint) pair. Instead, a pure unary helper (canonify_cpu / canonify_mem
from library/general/containerlimits/src.rego, split parts, prefix strips)
is evaluated ONCE per interned vocab entry on the host — via the Rego
interpreter for module functions — and shipped to the device as columns
indexed by string id. The cross-product sweep then costs one gather, the
same hoisting trick the match tables use for string predicates
(ops/strtab.py): O(vocab) host work outside the hot loop instead of
O(objects × constraints) interpreted calls inside it (the reference's cost
shape, vendor/.../opa/topdown).

Columns extend lazily as the vocab grows, keyed by the same epoch scheme
as MatchTables.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from .strtab import StringTable, canon_num

UNDEF = object()

# kind codes mirrored from ir/prog.py (no import cycle: ops is below ir)
_K_ABSENT = 0
_K_NULL = 1
_K_FALSE = 2
_K_TRUE = 3
_K_NUM = 4
_K_STR = 5


def decode_vocab(s: str) -> Any:
    """Interned vocab entry -> the value it stands for. Numbers are
    interned as canonical "\\x01n<repr>" strings (strtab.canon_num)."""
    if s.startswith("\x01n"):
        body = s[2:]
        try:
            return int(body)
        except ValueError:
            return float(body)
    if s.startswith("\x00"):
        return UNDEF  # pad entry
    return s


def split_part(sep: str, i: int, k: int) -> Callable[[Any], Any]:
    """Part i of split(s, sep), defined only for exactly-k-part splits —
    the definedness of part 0 doubles as the destructure arity guard."""

    def fn(v: Any) -> Any:
        if not isinstance(v, str):
            return UNDEF
        parts = v.split(sep)
        if len(parts) != k:
            return UNDEF
        return parts[i]

    return fn


def strip_prefix(prefix: str) -> Callable[[Any], Any]:
    def fn(v: Any) -> Any:
        if not isinstance(v, str) or not v.startswith(prefix):
            return UNDEF
        return v[len(prefix):]

    return fn


def builtin_unary(name: str) -> Callable[[Any], Any]:
    """Host image of a unary Rego builtin (compile.py _BUILTIN_DERIVED,
    e.g. to_number — vendor/.../opa/topdown/casts.go). Raising -> UNDEF
    via materialize()'s exception guard."""
    from ..rego.builtins import BUILTINS

    return BUILTINS[(name,)]


class DerivedTables:
    """Per-driver cache of derived columns over the shared vocab.

    Chain-depth cap: derived OUTPUTS intern new vocab entries (canonical
    number strings, stripped prefixes, ...). Those entries themselves need
    derived coverage only when programs chain derived calls
    (to_number(canonify(x)) — DerivedVal base can be a DerivedVal), and
    chain depth is bounded by program nesting. Without a cap, each
    materialize pass would evaluate the fns over the previous pass's
    outputs and intern yet more entries — an unbounded vocab-growth loop
    (canonify outputs multiply by 1000 per generation) that also reshapes
    the match table and forces an XLA recompile EVERY audit."""

    MAX_CHAIN = 4

    def __init__(self, table: StringTable):
        self.table = table
        self._cols: dict[Any, int] = {}
        self._fns: list[Callable[[Any], Any]] = []
        self._data: list[dict[str, np.ndarray]] = []
        self._built: list[int] = []
        self._level: dict[int, int] = {}  # vocab id -> derivation depth
        # rows whose level just DROPPED (an output row later reached from
        # a shallower input — e.g. a level-4 chain artifact that a real
        # object value canonifies straight into): previously-skipped
        # entries must be re-evaluated or the device under-fires
        self._relower: set[int] = set()

    def _intern_out(self, s: str, level: int) -> int:
        new_level = level + 1
        before = len(self.table)
        i = self.table.intern(s)
        if i >= before:  # entry created by derived materialization
            self._level[i] = new_level
        elif self._level.get(i, 0) > new_level:
            self._level[i] = new_level
            self._relower.add(i)
        return i

    def col(self, key: Any, fn: Callable[[Any], Any]) -> int:
        c = self._cols.get(key)
        if c is None:
            c = len(self._fns)
            self._cols[key] = c
            self._fns.append(fn)
            self._data.append({
                "sid": np.zeros(0, dtype=np.int32),
                "num": np.zeros(0, dtype=np.float32),
                "nid": np.zeros(0, dtype=np.int32),
                "kind": np.zeros(0, dtype=np.int8),
            })
            self._built.append(0)
        return c

    def materialize(self, cols: list[int]) -> dict[int, dict[str, np.ndarray]]:
        """Extend the requested columns to the current vocab and return
        {col: {sid, num, nid, kind}} arrays of length V. Evaluating a fn
        may intern new output strings (growing the vocab); iterate to a
        fixpoint so chained derived programs (to_number(canonify(x)))
        see coverage for every base row, while the MAX_CHAIN depth cap
        keeps pure chain artifacts from growing the vocab forever. Rows
        whose level drops mid-pass (self._relower) are re-evaluated."""
        for _ in range(64):  # safety bound; the fixpoint is reached in
            changed = False  # chain-depth + 1 iterations
            for c in cols:
                changed |= self._extend_col(c)
            if self._relower:
                relower, self._relower = self._relower, set()
                for c in cols:
                    changed |= self._retry_col(c, relower)
                changed = True
            if not changed:
                break
        return {c: self._data[c] for c in cols}

    def _eval_row(self, c: int, i: int, arrs: dict, j: int) -> None:
        """Evaluate column c's fn for vocab row i into arrs at offset j."""
        level = self._level.get(i, 0)
        if level >= self.MAX_CHAIN:
            return  # depth cap: see class docstring
        v = decode_vocab(self.table.string(i))
        if v is UNDEF:
            return
        try:
            r = self._fns[c](v)
        except Exception:
            r = UNDEF
        if r is UNDEF:
            return
        if isinstance(r, bool):
            arrs["kind"][j] = _K_TRUE if r else _K_FALSE
            arrs["num"][j] = 1.0 if r else 0.0
        elif isinstance(r, (int, float)):
            arrs["kind"][j] = _K_NUM
            # clamp into f32 range rather than letting the cast overflow
            # to inf: distinct huge values collapse to the same f32 either
            # way (the nid tie-detection in evaljax keeps comparisons
            # over-firing), but inf would turn device arithmetic into nan
            # (inf - inf) which compares false on BOTH interval bounds —
            # an under-fire. Clamped values stay nan-free.
            arrs["num"][j] = min(max(float(r), -3.4e38), 3.4e38)
            arrs["nid"][j] = self._intern_out(canon_num(r), level)
        elif isinstance(r, str):
            arrs["kind"][j] = _K_STR
            arrs["sid"][j] = self._intern_out(r, level)
        elif r is None:
            arrs["kind"][j] = _K_NULL
        # arrays/objects: leave absent (no scalar image)

    def _extend_col(self, c: int) -> bool:
        V = len(self.table)
        built = self._built[c]
        if built >= V:
            return False
        n_new = V - built
        fresh = {
            "sid": np.zeros(n_new, dtype=np.int32),
            "num": np.full(n_new, np.nan, dtype=np.float32),
            "nid": np.zeros(n_new, dtype=np.int32),
            "kind": np.zeros(n_new, dtype=np.int8),
        }
        for j in range(n_new):
            i = built + j
            if i == 0:
                continue  # pad entry: absent
            self._eval_row(c, i, fresh, j)
        d = self._data[c]
        self._data[c] = {k: np.concatenate([d[k], fresh[k]])
                         for k in fresh}
        self._built[c] = V
        return True

    def _retry_col(self, c: int, rows: set[int]) -> bool:
        """Re-evaluate relowered rows already built as absent. Arrays are
        replaced (not mutated): device caches key on array identity."""
        built = self._built[c]
        todo = [i for i in rows
                if i < built and self._data[c]["kind"][i] == _K_ABSENT]
        if not todo:
            return False
        d = {k: a.copy() for k, a in self._data[c].items()}
        for i in todo:
            self._eval_row(c, i, d, i)
        self._data[c] = d
        return True


def interp_unary(module, name: str) -> Callable[[Any], Any]:
    """Host closure evaluating a module function of one argument via the
    Rego interpreter (the exact-semantics engine the host re-check uses)."""
    from ..rego.interp import Ctx, Interpreter, RegoError, UNDEF as R_UNDEF
    from ..utils.values import freeze, thaw

    interp = Interpreter({"m": module})

    def fn(v: Any) -> Any:
        ctx = Ctx(interp, None)
        try:
            r = interp._call_function(module.package, name, (freeze(v),), ctx)
        except RegoError:
            return UNDEF
        return UNDEF if r is R_UNDEF else thaw(r)

    return fn


def interp_pred(module, name: str, pattern_pos: int
                ) -> Callable[[str, list], np.ndarray]:
    """Match-table op closure for a binary boolean helper: rows are keyed
    by the pattern (parameter-side) string; the vector is the predicate
    over every vocab entry. pattern_pos says which formal receives the
    pattern."""
    from ..rego.interp import Ctx, Interpreter, RegoError, UNDEF as R_UNDEF
    from ..utils.values import freeze

    interp = Interpreter({"m": module})

    def op(pattern: str, strings: list) -> np.ndarray:
        out = np.zeros(len(strings), dtype=bool)
        fp = freeze(pattern)
        for i, s in enumerate(strings):
            v = decode_vocab(s)
            if v is UNDEF:
                continue
            args = (fp, freeze(v)) if pattern_pos == 0 else (freeze(v), fp)
            ctx = Ctx(interp, None)
            try:
                r = interp._call_function(module.package, name, args, ctx)
            except RegoError:
                continue
            out[i] = r is not R_UNDEF and r is not False
        return out

    return op
