"""Rego lexer.

Produces a token stream with explicit NEWLINE tokens: Rego rule and
comprehension bodies separate literals by newline or `;`, while newlines
inside parenthesized/bracketed terms are insignificant — the parser decides
which applies (see parser.py).
"""

from __future__ import annotations

from dataclasses import dataclass


class ScanError(SyntaxError):
    pass


@dataclass(frozen=True)
class Token:
    kind: str  # IDENT NUMBER STRING OP NEWLINE EOF
    value: object
    line: int
    col: int

    def __repr__(self):
        return f"{self.kind}({self.value!r})@{self.line}"


_TWO_CHAR = {":=", "==", "!=", "<=", ">="}
_ONE_CHAR = set("=<>+-*/%&|(){}[],:;.")

_ESCAPES = {
    '"': '"',
    "\\": "\\",
    "/": "/",
    "n": "\n",
    "t": "\t",
    "r": "\r",
    "b": "\b",
    "f": "\f",
}


def scan(src: str, name: str = "<rego>") -> list[Token]:
    toks: list[Token] = []
    i, line, col = 0, 1, 1
    n = len(src)

    def emit(kind, value, l, c):
        toks.append(Token(kind, value, l, c))

    while i < n:
        ch = src[i]
        if ch == "\n":
            if toks and toks[-1].kind not in ("NEWLINE",):
                emit("NEWLINE", None, line, col)
            i += 1
            line += 1
            col = 1
            continue
        if ch in " \t\r":
            i += 1
            col += 1
            continue
        if ch == "#":
            while i < n and src[i] != "\n":
                i += 1
            continue
        if ch == '"':
            l0, c0 = line, col
            i += 1
            col += 1
            buf = []
            while True:
                if i >= n:
                    raise ScanError(f"{name}:{l0}: unterminated string")
                c = src[i]
                if c == '"':
                    i += 1
                    col += 1
                    break
                if c == "\\":
                    if i + 1 >= n:
                        raise ScanError(f"{name}:{line}: bad escape")
                    e = src[i + 1]
                    if e == "u":
                        buf.append(chr(int(src[i + 2 : i + 6], 16)))
                        i += 6
                        col += 6
                        continue
                    if e not in _ESCAPES:
                        raise ScanError(f"{name}:{line}: bad escape \\{e}")
                    buf.append(_ESCAPES[e])
                    i += 2
                    col += 2
                    continue
                if c == "\n":
                    raise ScanError(f"{name}:{l0}: newline in string")
                buf.append(c)
                i += 1
                col += 1
            emit("STRING", "".join(buf), l0, c0)
            continue
        if ch == "`":  # raw string
            l0, c0 = line, col
            j = src.find("`", i + 1)
            if j < 0:
                raise ScanError(f"{name}:{l0}: unterminated raw string")
            raw = src[i + 1 : j]
            line += raw.count("\n")
            i = j + 1
            emit("STRING", raw, l0, c0)
            continue
        if ch.isdigit() or (ch == "." and i + 1 < n and src[i + 1].isdigit()):
            l0, c0 = line, col
            j = i
            while j < n and (src[j].isdigit() or src[j] in ".eE+-"):
                # stop at '.' followed by non-digit (ref dot), and at +/-
                # not preceded by e/E (binary operators)
                if src[j] == "." and not (j + 1 < n and src[j + 1].isdigit()):
                    break
                if src[j] in "+-" and src[j - 1] not in "eE":
                    break
                j += 1
            text = src[i:j]
            try:
                val = int(text)
            except ValueError:
                val = float(text)
            emit("NUMBER", val, l0, c0)
            col += j - i
            i = j
            continue
        if ch.isalpha() or ch == "_":
            l0, c0 = line, col
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            emit("IDENT", src[i:j], l0, c0)
            col += j - i
            i = j
            continue
        two = src[i : i + 2]
        if two in _TWO_CHAR:
            emit("OP", two, line, col)
            i += 2
            col += 2
            continue
        if ch in _ONE_CHAR:
            emit("OP", ch, line, col)
            i += 1
            col += 1
            continue
        raise ScanError(f"{name}:{line}:{col}: unexpected character {ch!r}")

    emit("EOF", None, line, col)
    return toks
