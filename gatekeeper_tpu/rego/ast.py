"""AST for the Rego subset used by Gatekeeper policy libraries.

Grammar coverage is driven by the corpus this framework must run: the 23
ConstraintTemplates of the reference policy library and the target matcher
library (reference: pkg/target/regolib/src.rego, library/**/src.rego), plus
their test suites (src_test.rego). That means: packages, default rules,
complete/function/partial-set/partial-object rules with multiple clauses,
bodies of literals with `not` / `some` / `with ... as` modifiers, full terms
(scalars, refs with dynamic brackets, arrays, objects, sets, array/set/object
comprehensions, calls), unification and `:=` assignment, comparison /
arithmetic / set binops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class Node:
    pass


# ---------------------------------------------------------------- terms


@dataclass(frozen=True)
class Scalar(Node):
    value: Any  # None | bool | int | float | str


@dataclass(frozen=True)
class Var(Node):
    name: str  # wildcards are renamed to unique "$wc<N>" by the parser


@dataclass(frozen=True)
class Ref(Node):
    """base[arg0][arg1]...; `a.b.c` sugar becomes string-scalar brackets."""

    base: Node  # Var or parenthesized term / Call
    args: tuple  # of term nodes; Scalar(str) for dotted access


@dataclass(frozen=True)
class ArrayLit(Node):
    items: tuple


@dataclass(frozen=True)
class ObjectLit(Node):
    items: tuple  # of (key_term, value_term)


@dataclass(frozen=True)
class SetLit(Node):
    items: tuple


@dataclass(frozen=True)
class ArrayCompr(Node):
    head: Node
    body: tuple  # of Literal


@dataclass(frozen=True)
class SetCompr(Node):
    head: Node
    body: tuple


@dataclass(frozen=True)
class ObjectCompr(Node):
    key: Node
    value: Node
    body: tuple


@dataclass(frozen=True)
class Call(Node):
    """fn(args...) — fn is a dotted name like ("re_match",) or ("glob","match")."""

    fn: tuple  # name path
    args: tuple


@dataclass(frozen=True)
class BinOp(Node):
    op: str  # == != < <= > >= + - * / % | &
    lhs: Node
    rhs: Node


@dataclass(frozen=True)
class UnaryMinus(Node):
    term: Node


# ---------------------------------------------------------------- literals


@dataclass(frozen=True)
class Assign(Node):
    lhs: Node
    rhs: Node


@dataclass(frozen=True)
class Unify(Node):
    lhs: Node
    rhs: Node


@dataclass(frozen=True)
class SomeDecl(Node):
    names: tuple  # of str


@dataclass(frozen=True)
class WithMod(Node):
    target: tuple  # ref path as names, e.g. ("input",) or ("data","inventory")
    value: Node


@dataclass(frozen=True)
class Literal(Node):
    expr: Node  # Assign | Unify | BinOp | Call | term | SomeDecl
    negated: bool = False
    withs: tuple = ()  # of WithMod
    line: int = 0


# ---------------------------------------------------------------- rules


@dataclass(frozen=True)
class Rule(Node):
    """One clause. Multiple clauses with the same name form a disjunction
    (partial rules union; complete/function rules must agree — OPA's
    "complete rules must not produce multiple outputs" semantics)."""

    name: str
    kind: str  # "complete" | "function" | "partial_set" | "partial_object"
    args: tuple = ()  # function formal-parameter terms
    key: Optional[Node] = None  # partial-set element / partial-object key
    value: Optional[Node] = None  # head value (None => Scalar(True))
    body: tuple = ()  # of Literal; () => always-true body
    is_default: bool = False
    line: int = 0


@dataclass(frozen=True)
class Module(Node):
    package: tuple  # of str, e.g. ("k8srequiredlabels",)
    imports: tuple = ()
    rules: tuple = ()
    source_name: str = "<module>"
