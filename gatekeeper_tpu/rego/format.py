"""Canonical Rego pretty-printer (the `opa fmt` analog).

Renders a parsed Module back to canonical Rego source: dotted refs where
legal, `:=` kept as written, one literal per body line, 2-space indent,
wildcards printed as `_`. The contract mirrors opa fmt's
(vendor/.../opa/format): output re-parses to the same AST (modulo
source positions and wildcard numbering) — pinned by the round-trip
tests over the reference library corpus.
"""

from __future__ import annotations

import json
import re

from . import ast as A

_IDENT = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_INFIX = {"==", "!=", "<", "<=", ">", ">=", "+", "-", "*", "/", "%",
          "|", "&"}
# binding strength only matters for the few nestings the corpus uses;
# parenthesize any nested binop conservatively
_KEYWORDS = {"not", "some", "with", "as", "default", "package", "import",
             "true", "false", "null", "else"}


def _scalar(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        if isinstance(v, float) and v.is_integer():
            return str(int(v))
        return repr(v)
    return json.dumps(v)


def _var(name: str) -> str:
    return "_" if name.startswith("$wc") else name


def term(t, parent_binop: bool = False) -> str:
    if isinstance(t, A.Scalar):
        return _scalar(t.value)
    if isinstance(t, A.Var):
        return _var(t.name)
    if isinstance(t, A.Ref):
        out = term(t.base)
        for a in t.args:
            if isinstance(a, A.Scalar) and isinstance(a.value, str) and \
                    _IDENT.match(a.value) and a.value not in _KEYWORDS:
                out += f".{a.value}"
            else:
                out += f"[{term(a)}]"
        return out
    if isinstance(t, A.Call):
        args = ", ".join(term(a) for a in t.args)
        return f"{'.'.join(t.fn)}({args})"
    if isinstance(t, A.BinOp):
        lhs = term(t.lhs, parent_binop=True)
        rhs = term(t.rhs, parent_binop=True)
        s = f"{lhs} {t.op} {rhs}"
        return f"({s})" if parent_binop else s
    if isinstance(t, A.UnaryMinus):
        return f"-{term(t.term, parent_binop=True)}"
    if isinstance(t, A.ArrayLit):
        return "[" + ", ".join(term(x) for x in t.items) + "]"
    if isinstance(t, A.SetLit):
        if not t.items:
            return "set()"
        return "{" + ", ".join(term(x) for x in t.items) + "}"
    if isinstance(t, A.ObjectLit):
        return "{" + ", ".join(f"{term(k)}: {term(v)}"
                               for k, v in t.items) + "}"
    if isinstance(t, A.ArrayCompr):
        return f"[{term(t.head)} | {_compr_body(t.body)}]"
    if isinstance(t, A.SetCompr):
        return f"{{{term(t.head)} | {_compr_body(t.body)}}}"
    if isinstance(t, A.ObjectCompr):
        return (f"{{{term(t.key)}: {term(t.value)} | "
                f"{_compr_body(t.body)}}}")
    if isinstance(t, A.Assign):
        return f"{term(t.lhs)} := {term(t.rhs)}"
    if isinstance(t, A.Unify):
        return f"{term(t.lhs)} = {term(t.rhs)}"
    if isinstance(t, A.SomeDecl):
        return "some " + ", ".join(_var(n) for n in t.names)
    raise TypeError(f"cannot format {type(t).__name__}")


def _literal(lit: A.Literal) -> str:
    body = term(lit.expr)
    if lit.negated:
        body = f"not {body}"
    for w in lit.withs:
        body += f" with {'.'.join(w.target)} as {term(w.value)}"
    return body


def _compr_body(body: tuple) -> str:
    return "; ".join(_literal(l) for l in body)


def _rule_head(r: A.Rule) -> str:
    head = r.name
    if r.kind == "function":
        head += "(" + ", ".join(term(a) for a in r.args) + ")"
    elif r.kind == "partial_set":
        head += f"[{term(r.key)}]"
    elif r.kind == "partial_object":
        head += f"[{term(r.key)}]"
    if r.kind == "partial_object":
        head += f" = {term(r.value)}"
    elif r.value is not None and not (isinstance(r.value, A.Scalar)
                                      and r.value.value is True):
        head += f" = {term(r.value)}"
    if r.is_default:
        head = f"default {head}"
    return head


def format_rule(r: A.Rule) -> str:
    head = _rule_head(r)
    if not r.body:
        return head
    lines = [head + " {"]
    for lit in r.body:
        lines.append(f"  {_literal(lit)}")
    lines.append("}")
    return "\n".join(lines)


def format_module(m: A.Module) -> str:
    out = ["package " + ".".join(m.package)]
    for imp in m.imports:
        out.append("import " + ".".join(imp) if isinstance(imp, tuple)
                   else f"import {imp}")
    out.append("")
    for r in m.rules:
        out.append(format_rule(r))
        out.append("")
    return "\n".join(out).rstrip() + "\n"
