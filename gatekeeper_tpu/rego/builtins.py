"""Builtin functions for the Rego subset.

Coverage is the builtin surface actually exercised by the reference policy
corpus (SURVEY.md §2.3): sprintf, count, to_number, is_* type checks,
substring, re_match, startswith/endswith/contains, replace, trim, split,
concat, min/max/sum, any/all, plus sort/lower/upper/abs for completeness.

Error semantics: a builtin raising BuiltinError makes the enclosing
expression *undefined* (the literal fails; under `not` it succeeds). This is
OPA's default non-strict builtin-error behavior that e.g.
k8scontainerlimits' `not canonify_cpu(cpu_orig)` relies on
(library/general/containerlimits/src.rego).
"""

from __future__ import annotations

import re
from typing import Any

from ..utils.values import FrozenDict, format_value, rego_eq, sort_key, type_name


class BuiltinError(Exception):
    pass


_REGEX_CACHE: dict[str, "re.Pattern[str]"] = {}


def compiled_regex(pattern: str) -> "re.Pattern[str]":
    pat = _REGEX_CACHE.get(pattern)
    if pat is None:
        try:
            pat = re.compile(pattern)
        except re.error as e:
            raise BuiltinError(f"invalid regex {pattern!r}: {e}") from None
        _REGEX_CACHE[pattern] = pat
    return pat


def _need(v: Any, ty: str, fn: str) -> Any:
    if type_name(v) != ty:
        raise BuiltinError(f"{fn}: expected {ty}, got {type_name(v)}")
    return v


def _need_str(v: Any, fn: str) -> str:
    return _need(v, "string", fn)


def _need_num(v: Any, fn: str):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise BuiltinError(f"{fn}: expected number, got {type_name(v)}")
    return v


def _iterable(v: Any, fn: str):
    if isinstance(v, (tuple, frozenset)):
        return list(v)
    if isinstance(v, FrozenDict):
        return list(v.values())
    raise BuiltinError(f"{fn}: expected collection, got {type_name(v)}")


def bi_count(v):
    if isinstance(v, str):
        return len(v)
    if isinstance(v, (tuple, frozenset, FrozenDict)):
        return len(v)
    raise BuiltinError(f"count: cannot count {type_name(v)}")


def bi_to_number(v):
    if v is None:
        return 0
    if isinstance(v, bool):
        return 1 if v else 0
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                f = float(v)
            except ValueError:
                raise BuiltinError(f"to_number: invalid number {v!r}") from None
            return int(f) if f.is_integer() else f
    raise BuiltinError(f"to_number: cannot convert {type_name(v)}")


def bi_substring(s, start, length):
    s = _need_str(s, "substring")
    start = int(_need_num(start, "substring"))
    length = int(_need_num(length, "substring"))
    if start < 0:
        raise BuiltinError("substring: negative start")
    if length < 0:
        return s[start:]
    return s[start : start + length]


def bi_sprintf(fmt, args):
    fmt = _need_str(fmt, "sprintf")
    args = list(_need(args, "array", "sprintf"))
    out = []
    i, n = 0, len(fmt)
    ai = 0
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        if i + 1 < n and fmt[i + 1] == "%":
            out.append("%")
            i += 2
            continue
        # parse verb (with optional width/precision, which we pass through to %-style)
        j = i + 1
        while j < n and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= n:
            raise BuiltinError("sprintf: trailing %")
        verb = fmt[j]
        spec = fmt[i + 1 : j]
        if ai >= len(args):
            raise BuiltinError("sprintf: not enough arguments")
        arg = args[ai]
        ai += 1
        if verb == "v":
            out.append(format_value(arg, top=True))
        elif verb == "s":
            out.append(arg if isinstance(arg, str) else format_value(arg, top=True))
        elif verb in "dxXob":
            out.append(("%" + spec + verb) % int(_need_num(arg, "sprintf")))
        elif verb in "feEgG":
            out.append(("%" + spec + verb) % float(_need_num(arg, "sprintf")))
        else:
            raise BuiltinError(f"sprintf: unsupported verb %{verb}")
        i = j + 1
    return "".join(out)


def bi_min(coll):
    items = _iterable(coll, "min")
    if not items:
        raise BuiltinError("min: empty collection")
    return min(items, key=sort_key)


def bi_max(coll):
    items = _iterable(coll, "max")
    if not items:
        raise BuiltinError("max: empty collection")
    return max(items, key=sort_key)


def bi_trim(s, cutset):
    return _need_str(s, "trim").strip(_need_str(cutset, "trim"))


def bi_concat(delim, coll):
    delim = _need_str(delim, "concat")
    items = coll if isinstance(coll, tuple) else sorted(coll, key=sort_key) if isinstance(coll, frozenset) else None
    if items is None:
        raise BuiltinError("concat: expected array or set")
    for x in items:
        _need_str(x, "concat")
    return delim.join(items)


def bi_any(coll):
    items = _iterable(coll, "any")
    return any(x is True for x in items)


def bi_all(coll):
    items = _iterable(coll, "all")
    return all(x is True for x in items)


BUILTINS: dict[tuple, Any] = {
    ("count",): bi_count,
    ("to_number",): bi_to_number,
    ("substring",): bi_substring,
    ("sprintf",): bi_sprintf,
    ("min",): bi_min,
    ("max",): bi_max,
    ("sum",): lambda c: sum(_need_num(x, "sum") for x in _iterable(c, "sum")),
    ("product",): lambda c: __import__("math").prod(
        _need_num(x, "product") for x in _iterable(c, "product")
    ),
    ("any",): bi_any,
    ("all",): bi_all,
    ("trim",): bi_trim,
    ("trim_space",): lambda s: _need_str(s, "trim_space").strip(),
    ("concat",): bi_concat,
    ("split",): lambda s, d: tuple(
        _need_str(s, "split").split(_need_str(d, "split"))
    ),
    ("replace",): lambda s, o, nw: _need_str(s, "replace").replace(
        _need_str(o, "replace"), _need_str(nw, "replace")
    ),
    ("startswith",): lambda s, p: _need_str(s, "startswith").startswith(
        _need_str(p, "startswith")
    ),
    ("endswith",): lambda s, p: _need_str(s, "endswith").endswith(
        _need_str(p, "endswith")
    ),
    ("contains",): lambda s, p: _need_str(p, "contains") in _need_str(s, "contains"),
    ("indexof",): lambda s, p: _need_str(s, "indexof").find(_need_str(p, "indexof")),
    ("lower",): lambda s: _need_str(s, "lower").lower(),
    ("upper",): lambda s: _need_str(s, "upper").upper(),
    ("format_int",): lambda v, b: {2: "{:b}", 8: "{:o}", 10: "{:d}", 16: "{:x}"}[
        int(_need_num(b, "format_int"))
    ].format(int(_need_num(v, "format_int"))),
    ("abs",): lambda v: abs(_need_num(v, "abs")),
    ("round",): lambda v: int(round(_need_num(v, "round"))),
    ("sort",): lambda c: tuple(sorted(_iterable(c, "sort"), key=sort_key)),
    ("to_string",): lambda v: format_value(v, top=True),
    ("re_match",): lambda p, v: bool(
        compiled_regex(_need_str(p, "re_match")).search(_need_str(v, "re_match"))
    ),
    ("regex", "match"): lambda p, v: bool(
        compiled_regex(_need_str(p, "regex.match")).search(
            _need_str(v, "regex.match")
        )
    ),
    ("is_string",): lambda v: isinstance(v, str),
    ("is_number",): lambda v: not isinstance(v, bool) and isinstance(v, (int, float)),
    ("is_boolean",): lambda v: isinstance(v, bool),
    ("is_null",): lambda v: v is None,
    ("is_array",): lambda v: isinstance(v, tuple),
    ("is_object",): lambda v: isinstance(v, FrozenDict),
    ("is_set",): lambda v: isinstance(v, frozenset),
    ("array", "concat"): lambda a, b: _need(a, "array", "array.concat")
    + _need(b, "array", "array.concat"),
    ("array", "slice"): lambda a, i, j: _need(a, "array", "array.slice")[
        int(_need_num(i, "array.slice")) : int(_need_num(j, "array.slice"))
    ],
    ("object", "get"): lambda o, k, d: o.get(k, d)
    if isinstance(o, FrozenDict)
    else d,
    ("equal",): rego_eq,
    ("neq",): lambda a, b: not rego_eq(a, b),
    ("cast_array",): lambda v: tuple(v)
    if isinstance(v, (tuple, frozenset))
    else (_ for _ in ()).throw(BuiltinError("cast_array")),
    ("cast_string",): lambda v: _need_str(v, "cast_string"),
    ("cast_boolean",): lambda v: _need(v, "boolean", "cast_boolean"),
    # debugging no-ops (OPA topdown/trace.go): always true so bodies continue
    ("trace",): lambda *a: True,
    ("print",): lambda *a: True,
}


# ------------------------------------------------- breadth batch (r3)
# Builtins beyond the reference corpus' needs, for policy portability:
# the OPA v0.2x surface k8s policies most commonly reach for.


def _bi_json_marshal(v):
    import json as _json

    from ..utils.values import thaw

    try:
        return _json.dumps(thaw(v), sort_keys=True,
                           separators=(",", ":"))
    except (TypeError, ValueError) as e:
        raise BuiltinError(f"json.marshal: {e}") from None


def _bi_json_unmarshal(s):
    import json as _json

    from ..utils.values import freeze

    try:
        return freeze(_json.loads(_need_str(s, "json.unmarshal")))
    except ValueError as e:
        raise BuiltinError(f"json.unmarshal: {e}") from None


def _b64(codec, name):
    import base64 as _b

    fn = getattr(_b, codec)

    def run(s):
        try:
            return fn(_need_str(s, name).encode()).decode()
        except Exception as e:  # noqa: BLE001
            raise BuiltinError(f"{name}: {e}") from None

    return run


def _b64dec(codec, name):
    import base64 as _b

    fn = getattr(_b, codec)

    def run(s):
        try:
            return fn(_need_str(s, name).encode()).decode()
        except Exception as e:  # noqa: BLE001
            raise BuiltinError(f"{name}: {e}") from None

    return run


def _bi_glob_match(pattern, delimiters, value):
    """OPA glob.match subset: *, **, ?, [classes], {alt,ernates};
    bare * and ? do not cross a delimiter (default ".")."""
    pattern = _need_str(pattern, "glob.match")
    value = _need_str(value, "glob.match")
    if delimiters is None:
        delims = ["."]
    else:
        delims = [_need_str(d, "glob.match")
                  for d in _iterable(delimiters, "glob.match")] or ["."]
    d = re.escape("".join(delims))
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
            else:
                out.append(f"[^{d}]*")
                i += 1
        elif c == "?":
            out.append(f"[^{d}]")
            i += 1
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j < 0:
                raise BuiltinError("glob.match: unterminated class")
            out.append(pattern[i:j + 1])
            i = j + 1
        elif c == "{":
            j = pattern.find("}", i + 1)
            if j < 0:
                raise BuiltinError("glob.match: unterminated alternates")
            alts = pattern[i + 1:j].split(",")
            out.append("(" + "|".join(re.escape(a) for a in alts) + ")")
            i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.fullmatch("".join(out), value) is not None


def _bi_numbers_range(a, b):
    lo = int(_need_num(a, "numbers.range"))
    hi = int(_need_num(b, "numbers.range"))
    step = 1 if hi >= lo else -1
    return tuple(range(lo, hi + step, step))


def _bi_union(sets):
    out: frozenset = frozenset()
    for s in _iterable(sets, "union"):
        out |= _need(s, "set", "union")
    return out


def _bi_intersection(sets):
    items = [_need(s, "set", "intersection")
             for s in _iterable(sets, "intersection")]
    if not items:
        return frozenset()
    out = items[0]
    for s in items[1:]:
        out &= s
    return out


def _trim_side(side):
    def run(s, cutset):
        v = _need_str(s, f"trim_{side}")
        cut = _need_str(cutset, f"trim_{side}")
        if not cut:
            return v
        return v.lstrip(cut) if side == "left" else v.rstrip(cut)

    return run


BUILTINS.update({
    ("json", "marshal"): _bi_json_marshal,
    ("json", "unmarshal"): _bi_json_unmarshal,
    ("base64", "encode"): _b64("b64encode", "base64.encode"),
    ("base64", "decode"): _b64dec("b64decode", "base64.decode"),
    ("base64url", "encode"): _b64("urlsafe_b64encode", "base64url.encode"),
    ("base64url", "decode"): _b64dec("urlsafe_b64decode",
                                     "base64url.decode"),
    ("glob", "match"): _bi_glob_match,
    ("numbers", "range"): _bi_numbers_range,
    ("union",): _bi_union,
    ("intersection",): _bi_intersection,
    ("type_name",): type_name,
    ("trim_left",): _trim_side("left"),
    ("trim_right",): _trim_side("right"),
    ("trim_prefix",): lambda s, p: _need_str(s, "trim_prefix")[
        len(_need_str(p, "trim_prefix")):]
    if _need_str(s, "trim_prefix").startswith(_need_str(p, "trim_prefix"))
    else _need_str(s, "trim_prefix"),
    ("trim_suffix",): lambda s, p: _need_str(s, "trim_suffix")[
        : len(_need_str(s, "trim_suffix")) - len(_need_str(p, "trim_suffix"))]
    if _need_str(p, "trim_suffix")
    and _need_str(s, "trim_suffix").endswith(_need_str(p, "trim_suffix"))
    else _need_str(s, "trim_suffix"),
})
