"""Builtin functions for the Rego subset.

Coverage: the builtin surface exercised by the reference policy corpus
(SURVEY.md §2.3) plus the commonly-used remainder of OPA's library —
117 builtins across strings/regex/aggregates/objects/encoding (json,
yaml, base64, hex, urlquery)/crypto (hashes, hmac)/time/units/net.cidr/
semver/bits/type checks. Semantics mirror OPA topdown
(vendor/.../opa/topdown/*.go); tests pin literal expected values.

Error semantics: a builtin raising BuiltinError makes the enclosing
expression *undefined* (the literal fails; under `not` it succeeds). This is
OPA's default non-strict builtin-error behavior that e.g.
k8scontainerlimits' `not canonify_cpu(cpu_orig)` relies on
(library/general/containerlimits/src.rego).
"""

from __future__ import annotations

import re
from typing import Any

from ..utils.values import FrozenDict, format_value, rego_eq, sort_key, type_name


class BuiltinError(Exception):
    pass


# builtins whose results must never be memoized (non-pure): the codegen
# purity analyses (arg-pure fmemo, review/params-pure rmemo/pmemo, the
# head-witness memo) all consult this set
NONDETERMINISTIC: set = {("time", "now_ns"), ("print",), ("trace",)}


_REGEX_CACHE: dict[str, "re.Pattern[str]"] = {}


def compiled_regex(pattern: str) -> "re.Pattern[str]":
    pat = _REGEX_CACHE.get(pattern)
    if pat is None:
        try:
            pat = re.compile(pattern)
        except re.error as e:
            raise BuiltinError(f"invalid regex {pattern!r}: {e}") from None
        _REGEX_CACHE[pattern] = pat
    return pat


def _need(v: Any, ty: str, fn: str) -> Any:
    if type_name(v) != ty:
        raise BuiltinError(f"{fn}: expected {ty}, got {type_name(v)}")
    return v


def _need_str(v: Any, fn: str) -> str:
    return _need(v, "string", fn)


def _need_num(v: Any, fn: str):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise BuiltinError(f"{fn}: expected number, got {type_name(v)}")
    return v


def _iterable(v: Any, fn: str):
    if isinstance(v, (tuple, frozenset)):
        return list(v)
    if isinstance(v, FrozenDict):
        return list(v.values())
    raise BuiltinError(f"{fn}: expected collection, got {type_name(v)}")


def bi_count(v):
    if isinstance(v, str):
        return len(v)
    if isinstance(v, (tuple, frozenset, FrozenDict)):
        return len(v)
    raise BuiltinError(f"count: cannot count {type_name(v)}")


def bi_to_number(v):
    if v is None:
        return 0
    if isinstance(v, bool):
        return 1 if v else 0
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                f = float(v)
            except ValueError:
                raise BuiltinError(f"to_number: invalid number {v!r}") from None
            return int(f) if f.is_integer() else f
    raise BuiltinError(f"to_number: cannot convert {type_name(v)}")


def bi_substring(s, start, length):
    s = _need_str(s, "substring")
    start = int(_need_num(start, "substring"))
    length = int(_need_num(length, "substring"))
    if start < 0:
        raise BuiltinError("substring: negative start")
    if length < 0:
        return s[start:]
    return s[start : start + length]


def bi_sprintf(fmt, args):
    fmt = _need_str(fmt, "sprintf")
    args = list(_need(args, "array", "sprintf"))
    out = []
    i, n = 0, len(fmt)
    ai = 0
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        if i + 1 < n and fmt[i + 1] == "%":
            out.append("%")
            i += 2
            continue
        # parse verb (with optional width/precision, which we pass through to %-style)
        j = i + 1
        while j < n and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= n:
            raise BuiltinError("sprintf: trailing %")
        verb = fmt[j]
        spec = fmt[i + 1 : j]
        if ai >= len(args):
            raise BuiltinError("sprintf: not enough arguments")
        arg = args[ai]
        ai += 1
        if verb == "v":
            out.append(format_value(arg, top=True))
        elif verb == "s":
            out.append(arg if isinstance(arg, str) else format_value(arg, top=True))
        elif verb in "dxXob":
            out.append(("%" + spec + verb) % int(_need_num(arg, "sprintf")))
        elif verb in "feEgG":
            out.append(("%" + spec + verb) % float(_need_num(arg, "sprintf")))
        else:
            raise BuiltinError(f"sprintf: unsupported verb %{verb}")
        i = j + 1
    return "".join(out)


def bi_min(coll):
    items = _iterable(coll, "min")
    if not items:
        raise BuiltinError("min: empty collection")
    return min(items, key=sort_key)


def bi_max(coll):
    items = _iterable(coll, "max")
    if not items:
        raise BuiltinError("max: empty collection")
    return max(items, key=sort_key)


def bi_trim(s, cutset):
    return _need_str(s, "trim").strip(_need_str(cutset, "trim"))


def bi_concat(delim, coll):
    delim = _need_str(delim, "concat")
    items = coll if isinstance(coll, tuple) else sorted(coll, key=sort_key) if isinstance(coll, frozenset) else None
    if items is None:
        raise BuiltinError("concat: expected array or set")
    for x in items:
        _need_str(x, "concat")
    return delim.join(items)


def bi_any(coll):
    items = _iterable(coll, "any")
    return any(x is True for x in items)


def bi_all(coll):
    items = _iterable(coll, "all")
    return all(x is True for x in items)


BUILTINS: dict[tuple, Any] = {
    ("count",): bi_count,
    ("to_number",): bi_to_number,
    ("substring",): bi_substring,
    ("sprintf",): bi_sprintf,
    ("min",): bi_min,
    ("max",): bi_max,
    ("sum",): lambda c: sum(_need_num(x, "sum") for x in _iterable(c, "sum")),
    ("product",): lambda c: __import__("math").prod(
        _need_num(x, "product") for x in _iterable(c, "product")
    ),
    ("any",): bi_any,
    ("all",): bi_all,
    ("trim",): bi_trim,
    ("trim_space",): lambda s: _need_str(s, "trim_space").strip(),
    ("concat",): bi_concat,
    ("split",): lambda s, d: tuple(
        _need_str(s, "split").split(_need_str(d, "split"))
    ),
    ("replace",): lambda s, o, nw: _need_str(s, "replace").replace(
        _need_str(o, "replace"), _need_str(nw, "replace")
    ),
    ("startswith",): lambda s, p: _need_str(s, "startswith").startswith(
        _need_str(p, "startswith")
    ),
    ("endswith",): lambda s, p: _need_str(s, "endswith").endswith(
        _need_str(p, "endswith")
    ),
    ("contains",): lambda s, p: _need_str(p, "contains") in _need_str(s, "contains"),
    ("indexof",): lambda s, p: _need_str(s, "indexof").find(_need_str(p, "indexof")),
    ("lower",): lambda s: _need_str(s, "lower").lower(),
    ("upper",): lambda s: _need_str(s, "upper").upper(),
    ("format_int",): lambda v, b: {2: "{:b}", 8: "{:o}", 10: "{:d}", 16: "{:x}"}[
        int(_need_num(b, "format_int"))
    ].format(int(_need_num(v, "format_int"))),
    ("abs",): lambda v: abs(_need_num(v, "abs")),
    ("round",): lambda v: int(round(_need_num(v, "round"))),
    ("sort",): lambda c: tuple(sorted(_iterable(c, "sort"), key=sort_key)),
    ("to_string",): lambda v: format_value(v, top=True),
    ("re_match",): lambda p, v: bool(
        compiled_regex(_need_str(p, "re_match")).search(_need_str(v, "re_match"))
    ),
    ("regex", "match"): lambda p, v: bool(
        compiled_regex(_need_str(p, "regex.match")).search(
            _need_str(v, "regex.match")
        )
    ),
    ("is_string",): lambda v: isinstance(v, str),
    ("is_number",): lambda v: not isinstance(v, bool) and isinstance(v, (int, float)),
    ("is_boolean",): lambda v: isinstance(v, bool),
    ("is_null",): lambda v: v is None,
    ("is_array",): lambda v: isinstance(v, tuple),
    ("is_object",): lambda v: isinstance(v, FrozenDict),
    ("is_set",): lambda v: isinstance(v, frozenset),
    ("array", "concat"): lambda a, b: _need(a, "array", "array.concat")
    + _need(b, "array", "array.concat"),
    ("array", "slice"): lambda a, i, j: _need(a, "array", "array.slice")[
        int(_need_num(i, "array.slice")) : int(_need_num(j, "array.slice"))
    ],
    ("object", "get"): lambda o, k, d: o.get(k, d)
    if isinstance(o, FrozenDict)
    else d,
    ("equal",): rego_eq,
    ("neq",): lambda a, b: not rego_eq(a, b),
    ("cast_array",): lambda v: tuple(v)
    if isinstance(v, (tuple, frozenset))
    else (_ for _ in ()).throw(BuiltinError("cast_array")),
    ("cast_string",): lambda v: _need_str(v, "cast_string"),
    ("cast_boolean",): lambda v: _need(v, "boolean", "cast_boolean"),
    # debugging no-ops (OPA topdown/trace.go): always true so bodies continue
    ("trace",): lambda *a: True,
    ("print",): lambda *a: True,
}


# ------------------------------------------------- breadth batch (r3)
# Builtins beyond the reference corpus' needs, for policy portability:
# the OPA v0.2x surface k8s policies most commonly reach for.


def _bi_json_marshal(v):
    import json as _json

    from ..utils.values import thaw

    try:
        return _json.dumps(thaw(v), sort_keys=True,
                           separators=(",", ":"))
    except (TypeError, ValueError) as e:
        raise BuiltinError(f"json.marshal: {e}") from None


def _bi_json_unmarshal(s):
    import json as _json

    from ..utils.values import freeze

    try:
        return freeze(_json.loads(_need_str(s, "json.unmarshal")))
    except ValueError as e:
        raise BuiltinError(f"json.unmarshal: {e}") from None


def _b64(codec, name):
    import base64 as _b

    fn = getattr(_b, codec)

    def run(s):
        try:
            return fn(_need_str(s, name).encode()).decode()
        except Exception as e:  # noqa: BLE001
            raise BuiltinError(f"{name}: {e}") from None

    return run


def _b64dec(codec, name):
    import base64 as _b

    fn = getattr(_b, codec)

    def run(s):
        try:
            return fn(_need_str(s, name).encode()).decode()
        except Exception as e:  # noqa: BLE001
            raise BuiltinError(f"{name}: {e}") from None

    return run


def _bi_glob_match(pattern, delimiters, value):
    """OPA glob.match subset: *, **, ?, [classes], {alt,ernates};
    bare * and ? do not cross a delimiter (default ".")."""
    pattern = _need_str(pattern, "glob.match")
    value = _need_str(value, "glob.match")
    if delimiters is None:
        delims = ["."]
    else:
        delims = [_need_str(d, "glob.match")
                  for d in _iterable(delimiters, "glob.match")] or ["."]
    d = re.escape("".join(delims))
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
            else:
                out.append(f"[^{d}]*")
                i += 1
        elif c == "?":
            out.append(f"[^{d}]")
            i += 1
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j < 0:
                raise BuiltinError("glob.match: unterminated class")
            out.append(pattern[i:j + 1])
            i = j + 1
        elif c == "{":
            j = pattern.find("}", i + 1)
            if j < 0:
                raise BuiltinError("glob.match: unterminated alternates")
            alts = pattern[i + 1:j].split(",")
            out.append("(" + "|".join(re.escape(a) for a in alts) + ")")
            i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.fullmatch("".join(out), value) is not None


def _bi_numbers_range(a, b):
    lo = int(_need_num(a, "numbers.range"))
    hi = int(_need_num(b, "numbers.range"))
    step = 1 if hi >= lo else -1
    return tuple(range(lo, hi + step, step))


def _bi_union(sets):
    out: frozenset = frozenset()
    for s in _iterable(sets, "union"):
        out |= _need(s, "set", "union")
    return out


def _bi_intersection(sets):
    items = [_need(s, "set", "intersection")
             for s in _iterable(sets, "intersection")]
    if not items:
        return frozenset()
    out = items[0]
    for s in items[1:]:
        out &= s
    return out


def _trim_side(side):
    def run(s, cutset):
        v = _need_str(s, f"trim_{side}")
        cut = _need_str(cutset, f"trim_{side}")
        if not cut:
            return v
        return v.lstrip(cut) if side == "left" else v.rstrip(cut)

    return run


BUILTINS.update({
    ("json", "marshal"): _bi_json_marshal,
    ("json", "unmarshal"): _bi_json_unmarshal,
    ("base64", "encode"): _b64("b64encode", "base64.encode"),
    ("base64", "decode"): _b64dec("b64decode", "base64.decode"),
    ("base64url", "encode"): _b64("urlsafe_b64encode", "base64url.encode"),
    ("base64url", "decode"): _b64dec("urlsafe_b64decode",
                                     "base64url.decode"),
    ("glob", "match"): _bi_glob_match,
    ("numbers", "range"): _bi_numbers_range,
    ("union",): _bi_union,
    ("intersection",): _bi_intersection,
    ("type_name",): type_name,
    ("trim_left",): _trim_side("left"),
    ("trim_right",): _trim_side("right"),
    ("trim_prefix",): lambda s, p: _need_str(s, "trim_prefix")[
        len(_need_str(p, "trim_prefix")):]
    if _need_str(s, "trim_prefix").startswith(_need_str(p, "trim_prefix"))
    else _need_str(s, "trim_prefix"),
    ("trim_suffix",): lambda s, p: _need_str(s, "trim_suffix")[
        : len(_need_str(s, "trim_suffix")) - len(_need_str(p, "trim_suffix"))]
    if _need_str(p, "trim_suffix")
    and _need_str(s, "trim_suffix").endswith(_need_str(p, "trim_suffix"))
    else _need_str(s, "trim_suffix"),
})


# ---- breadth batch 2 (round 4): the commonly-used remainder of OPA's
# builtin surface (vendor/.../opa/topdown/*.go semantics; frozen values
# in and out, BuiltinError -> undefined)

import base64 as _base64
import binascii as _binascii
import json
import datetime as _dt
import hashlib as _hashlib
import hmac as _hmac_mod
import ipaddress as _ipaddress
import math as _math
import time as _time
import urllib.parse as _urlparse

from ..utils.values import freeze, thaw


def _bi_object_keys(o):
    _need(o, "object", "object.keys")
    return frozenset(o.keys())


def _bi_object_remove(o, ks):
    _need(o, "object", "object.remove")
    drop = set(_iterable(ks, "object.remove"))
    return FrozenDict((k, v) for k, v in o.items()
                      if not any(rego_eq(k, d) for d in drop))


def _bi_object_filter(o, ks):
    _need(o, "object", "object.filter")
    keep = set(_iterable(ks, "object.filter"))
    return FrozenDict((k, v) for k, v in o.items()
                      if any(rego_eq(k, d) for d in keep))


def _bi_object_union(a, b):
    _need(a, "object", "object.union")
    _need(b, "object", "object.union")

    def merge(x, y):
        if isinstance(x, FrozenDict) and isinstance(y, FrozenDict):
            out = dict(x)
            for k, v in y.items():
                out[k] = merge(out[k], v) if k in out else v
            return FrozenDict(out)
        return y

    return merge(a, b)


def _bi_object_union_n(objs):
    items = _iterable(objs, "object.union_n")
    out = FrozenDict()
    for o in items:
        out = _bi_object_union(out, _need(o, "object", "object.union_n"))
    return out


def _bi_regex_split(pattern, s):
    return tuple(compiled_regex(_need_str(pattern, "regex.split")).split(
        _need_str(s, "regex.split")))


def _bi_regex_is_valid(pattern):
    if not isinstance(pattern, str):
        return False
    try:
        re.compile(pattern)
        return True
    except re.error:
        return False


_GO_REF = re.compile(r"\$(\$|\{[A-Za-z0-9_]+\}|[A-Za-z0-9_]+)")


def _go_expand(template: str, m: "re.Match") -> str:
    """Go regexp.Expand: $1/${name} are submatch references; $$ is a
    literal $; unknown groups expand to the empty string."""
    def ref(rm):
        name = rm.group(1)
        if name == "$":
            return "$"
        if name.startswith("{"):
            name = name[1:-1]
        try:
            if name.isdigit():
                idx = int(name)
                if idx > m.re.groups:
                    return ""
                return m.group(idx) or ""
            return m.group(name) or ""
        except IndexError:  # unknown group: empty (Go Expand)
            return ""
    return _GO_REF.sub(ref, template)


def _bi_regex_replace(s, pattern, value):
    pat = compiled_regex(_need_str(pattern, "regex.replace"))
    tmpl = _need_str(value, "regex.replace")
    return pat.sub(lambda m: _go_expand(tmpl, m),
                   _need_str(s, "regex.replace"))


def _bi_regex_find_n(pattern, s, n):
    pat = compiled_regex(_need_str(pattern, "regex.find_n"))
    cnt = int(_need_num(n, "regex.find_n"))
    out = [m.group(0) for m in pat.finditer(_need_str(s, "regex.find_n"))]
    return tuple(out if cnt < 0 else out[:cnt])


def _bi_strings_reverse(s):
    return _need_str(s, "strings.reverse")[::-1]


def _bi_strings_count(s, sub):
    return _need_str(s, "strings.count").count(
        _need_str(sub, "strings.count"))


def _bi_indexof_n(s, sub):
    h = _need_str(s, "indexof_n")
    n = _need_str(sub, "indexof_n")
    out, i = [], h.find(n)
    while i != -1:
        out.append(i)
        i = h.find(n, i + 1)
    return tuple(out)


def _bi_replace_n(patterns, s):
    _need(patterns, "object", "strings.replace_n")
    out = _need_str(s, "strings.replace_n")
    for old, new in patterns.items():
        out = out.replace(_need_str(old, "strings.replace_n"),
                          _need_str(new, "strings.replace_n"))
    return out


def _bi_any_prefix_match(search, base):
    ss = [search] if isinstance(search, str) else \
        _iterable(search, "strings.any_prefix_match")
    bs = [base] if isinstance(base, str) else \
        _iterable(base, "strings.any_prefix_match")
    return any(_need_str(s, "strings.any_prefix_match").startswith(
        _need_str(b, "strings.any_prefix_match")) for s in ss for b in bs)


def _bi_any_suffix_match(search, base):
    ss = [search] if isinstance(search, str) else \
        _iterable(search, "strings.any_suffix_match")
    bs = [base] if isinstance(base, str) else \
        _iterable(base, "strings.any_suffix_match")
    return any(_need_str(s, "strings.any_suffix_match").endswith(
        _need_str(b, "strings.any_suffix_match")) for s in ss for b in bs)


def _bi_hex_encode(s):
    return _need_str(s, "hex.encode").encode().hex()


def _bi_hex_decode(s):
    try:
        return bytes.fromhex(_need_str(s, "hex.decode")).decode()
    except (ValueError, UnicodeDecodeError) as e:
        raise BuiltinError(f"hex.decode: {e}") from None


def _bi_urlquery_encode(s):
    return _urlparse.quote_plus(_need_str(s, "urlquery.encode"))


def _bi_urlquery_decode(s):
    return _urlparse.unquote_plus(_need_str(s, "urlquery.decode"))


def _bi_urlquery_encode_object(o):
    _need(o, "object", "urlquery.encode_object")
    parts = []
    for k, v in o.items():
        key = _urlparse.quote_plus(_need_str(k, "urlquery.encode_object"))
        vals = [v] if isinstance(v, str) else \
            _iterable(v, "urlquery.encode_object")
        for x in vals:
            parts.append(f"{key}="
                         f"{_urlparse.quote_plus(_need_str(x, 'urlquery'))}")
    return "&".join(parts)


def _bi_urlquery_decode_object(s):
    parsed = _urlparse.parse_qs(_need_str(s, "urlquery.decode_object"),
                                keep_blank_values=True)
    return FrozenDict((k, tuple(v)) for k, v in parsed.items())


def _bi_json_is_valid(s):
    if not isinstance(s, str):
        return False
    try:
        json.loads(s)
        return True
    except ValueError:
        return False


def _bi_yaml_marshal(v):
    import yaml as _yaml
    return _yaml.safe_dump(thaw(v), default_flow_style=False)


def _bi_yaml_unmarshal(s):
    import yaml as _yaml
    try:
        return freeze(_yaml.safe_load(_need_str(s, "yaml.unmarshal")))
    except _yaml.YAMLError as e:
        raise BuiltinError(f"yaml.unmarshal: {e}") from None


def _bi_yaml_is_valid(s):
    import yaml as _yaml
    if not isinstance(s, str):
        return False
    try:
        _yaml.safe_load(s)
        return True
    except _yaml.YAMLError:
        return False


def _bi_base64_is_valid(s):
    if not isinstance(s, str):
        return False
    try:
        _base64.b64decode(s, validate=True)
        return True
    except (_binascii.Error, ValueError):
        return False


def _hash(algo):
    def run(s):
        return getattr(_hashlib, algo)(
            _need_str(s, f"crypto.{algo}").encode()).hexdigest()
    return run


def _hmac(algo):
    def run(s, key):
        return _hmac_new(_need_str(key, f"crypto.hmac.{algo}"),
                         _need_str(s, f"crypto.hmac.{algo}"), algo)
    return run


def _hmac_new(key: str, msg: str, algo: str) -> str:
    return _hmac_mod.new(key.encode(), msg.encode(),
                         getattr(_hashlib, algo)).hexdigest()


def _bi_ceil(x):
    return int(_math.ceil(_need_num(x, "ceil")))


def _bi_floor(x):
    return int(_math.floor(_need_num(x, "floor")))


def _bi_numbers_range_step(a, b, step):
    lo = _need_num(a, "numbers.range_step")
    hi = _need_num(b, "numbers.range_step")
    st = _need_num(step, "numbers.range_step")
    if not float(st).is_integer() or st <= 0:
        raise BuiltinError("numbers.range_step: step must be a positive "
                           "integer")
    st = int(st)
    if lo <= hi:
        return tuple(range(int(lo), int(hi) + 1, st))
    return tuple(range(int(lo), int(hi) - 1, -st))


def _bi_array_reverse(a):
    _need(a, "array", "array.reverse")
    return tuple(reversed(a))


def _bi_time_now_ns():
    return int(_time.time() * 1e9)


_FRAC_RE = re.compile(r"\.(\d+)")


def _bi_parse_rfc3339_ns(s):
    v = _need_str(s, "time.parse_rfc3339_ns")
    try:
        if v.endswith("Z"):
            v = v[:-1] + "+00:00"
        frac_ns = 0
        fm = _FRAC_RE.search(v)
        if fm:
            digits = fm.group(1)[:9]
            frac_ns = int(digits.ljust(9, "0"))
            v = v[: fm.start()] + v[fm.end():]
        dt = _dt.datetime.fromisoformat(v)
    except ValueError as e:
        raise BuiltinError(f"time.parse_rfc3339_ns: {e}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp()) * 10**9 + frac_ns


def _ns_to_dt(ns) -> "_dt.datetime":
    # integer split: float division of ~1e18 ns loses sub-us precision
    s, rem = divmod(int(_need_num(ns, "time")), 10**9)
    return _dt.datetime.fromtimestamp(s, tz=_dt.timezone.utc).replace(
        microsecond=rem // 1000)


def _bi_time_date(ns):
    d = _ns_to_dt(ns)
    return (d.year, d.month, d.day)


def _bi_time_clock(ns):
    d = _ns_to_dt(ns)
    return (d.hour, d.minute, d.second)


def _bi_time_weekday(ns):
    return _ns_to_dt(ns).strftime("%A")


def _bi_time_add_date(ns, years, months, days):
    d = _ns_to_dt(ns)
    y = int(_need_num(years, "time.add_date"))
    mo = int(_need_num(months, "time.add_date"))
    dd = int(_need_num(days, "time.add_date"))
    month0 = d.month - 1 + mo
    year = d.year + y + month0 // 12
    month = month0 % 12 + 1
    # Go's AddDate normalizes out-of-range days by rolling over
    day = d.day
    base = _dt.datetime(year, month, 1, d.hour, d.minute, d.second,
                        d.microsecond, tzinfo=_dt.timezone.utc)
    out = base + _dt.timedelta(days=day - 1 + dd)
    return int(out.timestamp()) * 10**9 + out.microsecond * 1000


_UNITS = {"": 1, "k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12,
          "p": 10**15, "e": 10**18,
          "ki": 2**10, "mi": 2**20, "gi": 2**30, "ti": 2**40,
          "pi": 2**50, "ei": 2**60}


def _parse_units(s: str, fn: str, milli_ok: bool, bytes_ok: bool):
    v = _need_str(s, fn).strip().strip('"')
    if not v:
        raise BuiltinError(f"{fn}: no amount provided")
    i = len(v)
    while i > 0 and not (v[i - 1].isdigit() or v[i - 1] == "."):
        i -= 1
    num, raw = v[:i], v[i:]
    if not num:
        raise BuiltinError(f"{fn}: no amount provided")
    try:
        base = float(num) if "." in num else int(num)
    except ValueError as e:
        raise BuiltinError(f"{fn}: {e}") from None
    if milli_ok and raw == "m":  # case-sensitive: 'M' is mega, 'm' milli
        return base / 1000
    suffix = raw.lower()
    if bytes_ok:  # only parse_bytes accepts b/KB/KiB spellings
        if suffix == "b":
            suffix = ""
        elif suffix.endswith("b") and suffix[:-1] in _UNITS:
            suffix = suffix[:-1]
    if suffix not in _UNITS:
        raise BuiltinError(f"{fn}: unknown unit suffix {raw!r}")
    out = base * _UNITS[suffix]
    return int(out) if float(out).is_integer() else out


def _bi_units_parse(s):
    # decimal k/M/G... and binary Ki/Mi/Gi... (no bytes 'b' suffix)
    return _parse_units(s, "units.parse", milli_ok=True, bytes_ok=False)


def _bi_units_parse_bytes(s):
    return int(_parse_units(s, "units.parse_bytes", milli_ok=False,
                            bytes_ok=True))


def _net(v, fn):
    try:
        s = _need_str(v, fn)
        if "/" in s:
            return _ipaddress.ip_network(s, strict=False)
        return _ipaddress.ip_network(s + "/32" if ":" not in s
                                     else s + "/128", strict=False)
    except ValueError as e:
        raise BuiltinError(f"{fn}: {e}") from None


def _bi_cidr_contains(cidr, x):
    net = _net(cidr, "net.cidr_contains")
    other = _net(x, "net.cidr_contains")
    try:
        return other.subnet_of(net)
    except TypeError as e:  # mixed IPv4/IPv6: undefined, not a crash
        raise BuiltinError(f"net.cidr_contains: {e}") from None


def _bi_cidr_intersects(a, b):
    try:
        return _net(a, "net.cidr_intersects").overlaps(
            _net(b, "net.cidr_intersects"))
    except TypeError as e:
        raise BuiltinError(f"net.cidr_intersects: {e}") from None


def _bi_cidr_is_valid(v):
    if not isinstance(v, str):
        return False
    try:
        _ipaddress.ip_network(v, strict=False)
        return True
    except ValueError:
        return False


_SEMVER = re.compile(
    r"^(\d+)\.(\d+)\.(\d+)(?:-([0-9A-Za-z.-]+))?(?:\+[0-9A-Za-z.-]+)?$")


def _semver_key(v: str, fn: str):
    m = _SEMVER.match(_need_str(v, fn))
    if not m:
        raise BuiltinError(f"{fn}: invalid semver {v!r}")
    major, minor, patch = int(m.group(1)), int(m.group(2)), int(m.group(3))
    pre = m.group(4)
    if pre is None:
        pre_key = (1,)  # releases sort after any pre-release
    else:
        parts = []
        for p in pre.split("."):
            parts.append((0, int(p)) if p.isdigit() else (1, p))
        pre_key = (0, tuple(parts))
    return (major, minor, patch, pre_key)


def _bi_semver_is_valid(v):
    return isinstance(v, str) and bool(_SEMVER.match(v))


def _bi_semver_compare(a, b):
    ka = _semver_key(a, "semver.compare")
    kb = _semver_key(b, "semver.compare")
    return -1 if ka < kb else (1 if ka > kb else 0)


def _bits(fn_name, op):
    def run(a, b):
        x = _need_num(a, fn_name)
        y = _need_num(b, fn_name)
        if not float(x).is_integer() or not float(y).is_integer():
            raise BuiltinError(f"{fn_name}: operands must be integers")
        return op(int(x), int(y))
    return run


BUILTINS.update({
    ("object", "keys"): _bi_object_keys,
    ("object", "remove"): _bi_object_remove,
    ("object", "filter"): _bi_object_filter,
    ("object", "union"): _bi_object_union,
    ("object", "union_n"): _bi_object_union_n,
    ("regex", "split"): _bi_regex_split,
    ("regex", "is_valid"): _bi_regex_is_valid,
    ("regex", "replace"): _bi_regex_replace,
    ("regex", "find_n"): _bi_regex_find_n,
    ("strings", "reverse"): _bi_strings_reverse,
    ("strings", "count"): _bi_strings_count,
    ("strings", "replace_n"): _bi_replace_n,
    ("strings", "any_prefix_match"): _bi_any_prefix_match,
    ("strings", "any_suffix_match"): _bi_any_suffix_match,
    ("indexof_n",): _bi_indexof_n,
    ("hex", "encode"): _bi_hex_encode,
    ("hex", "decode"): _bi_hex_decode,
    ("urlquery", "encode"): _bi_urlquery_encode,
    ("urlquery", "decode"): _bi_urlquery_decode,
    ("urlquery", "encode_object"): _bi_urlquery_encode_object,
    ("urlquery", "decode_object"): _bi_urlquery_decode_object,
    ("json", "is_valid"): _bi_json_is_valid,
    ("yaml", "marshal"): _bi_yaml_marshal,
    ("yaml", "unmarshal"): _bi_yaml_unmarshal,
    ("yaml", "is_valid"): _bi_yaml_is_valid,
    ("base64", "is_valid"): _bi_base64_is_valid,
    ("crypto", "md5"): _hash("md5"),
    ("crypto", "sha1"): _hash("sha1"),
    ("crypto", "sha256"): _hash("sha256"),
    ("crypto", "hmac", "md5"): _hmac("md5"),
    ("crypto", "hmac", "sha1"): _hmac("sha1"),
    ("crypto", "hmac", "sha256"): _hmac("sha256"),
    ("crypto", "hmac", "sha512"): _hmac("sha512"),
    ("crypto", "hmac", "equal"): lambda a, b: _hmac_mod.compare_digest(
        _need_str(a, "crypto.hmac.equal"), _need_str(b, "crypto.hmac.equal")),
    ("ceil",): _bi_ceil,
    ("floor",): _bi_floor,
    ("numbers", "range_step"): _bi_numbers_range_step,
    ("array", "reverse"): _bi_array_reverse,
    ("time", "now_ns"): _bi_time_now_ns,
    ("time", "parse_rfc3339_ns"): _bi_parse_rfc3339_ns,
    ("time", "date"): _bi_time_date,
    ("time", "clock"): _bi_time_clock,
    ("time", "weekday"): _bi_time_weekday,
    ("time", "add_date"): _bi_time_add_date,
    ("units", "parse"): _bi_units_parse,
    ("units", "parse_bytes"): _bi_units_parse_bytes,
    ("net", "cidr_contains"): _bi_cidr_contains,
    ("net", "cidr_intersects"): _bi_cidr_intersects,
    ("net", "cidr_is_valid"): _bi_cidr_is_valid,
    ("semver", "is_valid"): _bi_semver_is_valid,
    ("semver", "compare"): _bi_semver_compare,
    ("bits", "or"): _bits("bits.or", lambda a, b: a | b),
    ("bits", "and"): _bits("bits.and", lambda a, b: a & b),
    ("bits", "xor"): _bits("bits.xor", lambda a, b: a ^ b),
    ("bits", "lsh"): _bits("bits.lsh", lambda a, b: a << b),
    ("bits", "rsh"): _bits("bits.rsh", lambda a, b: a >> b),
    ("bits", "negate"): lambda a: ~int(_need_num(a, "bits.negate")),
})


# ---- breadth batch 3: json document surgery, graph traversal, jwt ----


def _split_json_path(p, fn: str):
    if isinstance(p, str):
        return tuple(seg for seg in p.split("/") if seg != "")
    if isinstance(p, tuple):
        return tuple(str(x) if not isinstance(x, str) else x for x in p)
    raise BuiltinError(f"{fn}: path must be a string or array")


def _paths_trie(paths, fn: str):
    trie: dict = {}
    for p in _iterable(paths, fn):
        node = trie
        for seg in _split_json_path(p, fn):
            node = node.setdefault(seg, {})
        node["\x00end"] = True
    return trie


def _step_into(v, seg: str):
    if isinstance(v, FrozenDict):
        if seg in v:
            return True, v[seg]
        return False, None
    if isinstance(v, tuple):
        try:
            i = int(seg)
        except ValueError:
            return False, None
        if 0 <= i < len(v):
            return True, v[i]
    return False, None


def _bi_json_filter(obj, paths):
    """Keep only the listed paths (OPA topdown/json.go Filter)."""
    _need(obj, "object", "json.filter")
    trie = _paths_trie(paths, "json.filter")

    def keep(v, node):
        if "\x00end" in node:
            return v
        if isinstance(v, FrozenDict):
            out = {}
            for k, child in node.items():
                if k == "\x00end":
                    continue
                present, sub = _step_into(v, k)
                if present:
                    kept = keep(sub, child)
                    if kept is not _MISSING_JSON:
                        out[k] = kept
            return FrozenDict(out)
        if isinstance(v, tuple):
            out = []
            # original index order, not trie insertion order
            for k, child in sorted(
                    ((k, c) for k, c in node.items() if k != "\x00end"),
                    key=lambda kv: int(kv[0]) if kv[0].isdigit() else 0):
                present, sub = _step_into(v, k)
                if present:
                    kept = keep(sub, child)
                    if kept is not _MISSING_JSON:
                        out.append(kept)
            return tuple(out)
        return _MISSING_JSON

    got = keep(obj, trie)
    return got if got is not _MISSING_JSON else FrozenDict()


_MISSING_JSON = object()


def _bi_json_remove(obj, paths):
    """Remove the listed paths (OPA topdown/json.go Remove)."""
    _need(obj, "object", "json.remove")
    trie = _paths_trie(paths, "json.remove")

    def strip(v, node):
        if "\x00end" in node:
            return _MISSING_JSON
        if isinstance(v, FrozenDict):
            out = {}
            for k, sub in v.items():
                child = node.get(k if isinstance(k, str) else str(k))
                if child is None:
                    out[k] = sub
                else:
                    kept = strip(sub, child)
                    if kept is not _MISSING_JSON:
                        out[k] = kept
            return FrozenDict(out)
        if isinstance(v, tuple):
            out = []
            for i, sub in enumerate(v):
                child = node.get(str(i))
                if child is None:
                    out.append(sub)
                else:
                    kept = strip(sub, child)
                    if kept is not _MISSING_JSON:
                        out.append(kept)
            return tuple(out)
        return v

    got = strip(obj, trie)
    return got if got is not _MISSING_JSON else FrozenDict()


def _bi_object_subset(sup, sub):
    """True when sub is a (recursive) subset of sup: objects by keys,
    sets by membership, arrays by subsequence (OPA object.subset)."""
    def check(a, b):
        if isinstance(b, FrozenDict) and isinstance(a, FrozenDict):
            return all(k in a and check(a[k], v) for k, v in b.items())
        if isinstance(b, frozenset) and isinstance(a, frozenset):
            return b <= a
        if isinstance(b, tuple) and isinstance(a, tuple):
            i = 0
            for x in a:
                if i < len(b) and rego_eq(x, b[i]):
                    i += 1
            return i == len(b)
        return rego_eq(a, b)

    return check(sup, sub)


def _bi_graph_reachable(graph, initial):
    """Node set reachable from `initial` over an adjacency object whose
    values are arrays/sets of neighbor keys (OPA graph.reachable)."""
    _need(graph, "object", "graph.reachable")
    frontier = list(_iterable(initial, "graph.reachable"))
    seen = set()
    while frontier:
        n = frontier.pop()
        if n in seen:
            continue
        seen.add(n)
        nbrs = graph.get(n)
        if isinstance(nbrs, (tuple, frozenset)):
            frontier.extend(nbrs)
    return frozenset(seen)


def _b64url_decode_pad(s: str, fn: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    try:
        return _base64.urlsafe_b64decode(s + pad)
    except (_binascii.Error, ValueError) as e:
        raise BuiltinError(f"{fn}: {e}") from None


def _bi_jwt_decode(token):
    """[header, payload, signature-hex] without verification (OPA
    io.jwt.decode, topdown/tokens.go)."""
    parts = _need_str(token, "io.jwt.decode").split(".")
    if len(parts) != 3:
        raise BuiltinError("io.jwt.decode: expected 3 '.'-separated parts")
    try:
        header = json.loads(_b64url_decode_pad(parts[0], "io.jwt.decode"))
        payload = json.loads(_b64url_decode_pad(parts[1], "io.jwt.decode"))
    except ValueError as e:
        raise BuiltinError(f"io.jwt.decode: {e}") from None
    sig = _b64url_decode_pad(parts[2], "io.jwt.decode").hex()
    return (freeze(header), freeze(payload), sig)


def _bi_jwt_verify_hs256(token, secret):
    parts = _need_str(token, "io.jwt.verify_hs256").split(".")
    if len(parts) != 3:
        return False
    mac = _hmac_mod.new(_need_str(secret, "io.jwt.verify_hs256").encode(),
                        f"{parts[0]}.{parts[1]}".encode(),
                        _hashlib.sha256).digest()
    return _hmac_mod.compare_digest(
        mac, _b64url_decode_pad(parts[2], "io.jwt.verify_hs256"))


BUILTINS.update({
    ("json", "filter"): _bi_json_filter,
    ("json", "remove"): _bi_json_remove,
    ("object", "subset"): _bi_object_subset,
    ("graph", "reachable"): _bi_graph_reachable,
    ("io", "jwt", "decode"): _bi_jwt_decode,
    ("io", "jwt", "verify_hs256"): _bi_jwt_verify_hs256,
    ("base64url", "encode_no_pad"): lambda s: _base64.urlsafe_b64encode(
        _need_str(s, "base64url.encode_no_pad").encode()
    ).decode().rstrip("="),
})


def _json_ptr(path: str, fn: str) -> list:
    if path == "":
        return []
    if not path.startswith("/"):
        raise BuiltinError(f"{fn}: path must start with '/'")
    return [seg.replace("~1", "/").replace("~0", "~")
            for seg in path.split("/")[1:]]


def _patch_apply(doc, segs: list, op: str, value, fn: str):
    """Immutable RFC 6902 add/remove/replace on frozen values."""
    if not segs:
        if op == "remove":
            raise BuiltinError(f"{fn}: cannot remove the root")
        return value
    seg = segs[0]
    if isinstance(doc, FrozenDict):
        if len(segs) == 1:
            d = dict(doc)
            if op == "remove":
                if seg not in d:
                    raise BuiltinError(f"{fn}: path not found: {seg}")
                d.pop(seg)
            elif op == "replace":
                if seg not in d:
                    raise BuiltinError(f"{fn}: path not found: {seg}")
                d[seg] = value
            else:  # add
                d[seg] = value
            return FrozenDict(d)
        if seg not in doc:
            raise BuiltinError(f"{fn}: path not found: {seg}")
        d = dict(doc)
        d[seg] = _patch_apply(doc[seg], segs[1:], op, value, fn)
        return FrozenDict(d)
    if isinstance(doc, tuple):
        if seg == "-" and op == "add" and len(segs) == 1:
            return doc + (value,)
        try:
            i = int(seg)
        except ValueError:
            raise BuiltinError(f"{fn}: bad array index {seg!r}") from None
        if not (0 <= i <= len(doc) - (0 if op == "add" else 1)):
            raise BuiltinError(f"{fn}: index {i} out of range")
        if len(segs) == 1:
            if op == "add":
                return doc[:i] + (value,) + doc[i:]
            if op == "remove":
                return doc[:i] + doc[i + 1:]
            return doc[:i] + (value,) + doc[i + 1:]
        return doc[:i] + (_patch_apply(doc[i], segs[1:], op, value, fn),) \
            + doc[i + 1:]
    raise BuiltinError(f"{fn}: cannot descend into {type_name(doc)}")


def _bi_json_patch(doc, patches):
    """RFC 6902 add/remove/replace/copy/move/test (OPA json.patch)."""
    fn = "json.patch"
    out = doc
    for p in _iterable(patches, fn):
        _need(p, "object", fn)
        op = p.get("op")
        path = _json_ptr(_need_str(p.get("path", ""), fn), fn)
        if op in ("add", "replace"):
            out = _patch_apply(out, path, op, p.get("value"), fn)
        elif op == "remove":
            out = _patch_apply(out, path, "remove", None, fn)
        elif op in ("copy", "move"):
            src = _json_ptr(_need_str(p.get("from", ""), fn), fn)
            node = out
            for seg in src:
                present, node = _step_into(node, seg)
                if not present:
                    raise BuiltinError(f"{fn}: from path not found")
            if op == "move":
                out = _patch_apply(out, src, "remove", None, fn)
            out = _patch_apply(out, path, "add", node, fn)
        elif op == "test":
            node = out
            for seg in path:
                present, node = _step_into(node, seg)
                if not present:
                    raise BuiltinError(f"{fn}: test path not found")
            if not rego_eq(node, p.get("value")):
                raise BuiltinError(f"{fn}: test failed")
        else:
            raise BuiltinError(f"{fn}: unsupported op {op!r}")
    return out


def _bi_time_diff(a, b):
    """[years, months, days, hours, minutes, seconds] between two ns
    timestamps (OPA time.diff, Go-style civil difference)."""
    d1 = _ns_to_dt(a)
    d2 = _ns_to_dt(b)
    if d1 < d2:
        d1, d2 = d2, d1
    y = d1.year - d2.year
    mo = d1.month - d2.month
    dd = d1.day - d2.day
    hh = d1.hour - d2.hour
    mi = d1.minute - d2.minute
    ss = d1.second - d2.second
    if ss < 0:
        ss += 60
        mi -= 1
    if mi < 0:
        mi += 60
        hh -= 1
    if hh < 0:
        hh += 24
        dd -= 1
    if dd < 0:
        prev_month_year = d1.year if d1.month > 1 else d1.year - 1
        prev_month = d1.month - 1 if d1.month > 1 else 12
        import calendar as _cal
        dd += _cal.monthrange(prev_month_year, prev_month)[1]
        mo -= 1
    if mo < 0:
        mo += 12
        y -= 1
    return (y, mo, dd, hh, mi, ss)


BUILTINS.update({
    ("json", "patch"): _bi_json_patch,
    ("time", "diff"): _bi_time_diff,
})
