"""Builtin functions for the Rego subset.

Coverage: the builtin surface exercised by the reference policy corpus
(SURVEY.md §2.3) plus the commonly-used remainder of OPA's library —
117 builtins across strings/regex/aggregates/objects/encoding (json,
yaml, base64, hex, urlquery)/crypto (hashes, hmac)/time/units/net.cidr/
semver/bits/type checks. Semantics mirror OPA topdown
(vendor/.../opa/topdown/*.go); tests pin literal expected values.

Error semantics: a builtin raising BuiltinError makes the enclosing
expression *undefined* (the literal fails; under `not` it succeeds). This is
OPA's default non-strict builtin-error behavior that e.g.
k8scontainerlimits' `not canonify_cpu(cpu_orig)` relies on
(library/general/containerlimits/src.rego).
"""

from __future__ import annotations

import re
from typing import Any

from ..utils.values import FrozenDict, format_value, rego_eq, sort_key, type_name


class BuiltinError(Exception):
    pass


# builtins whose results must never be memoized (non-pure): the codegen
# purity analyses (arg-pure fmemo, review/params-pure rmemo/pmemo, the
# head-witness memo) all consult this set
NONDETERMINISTIC: set = {("time", "now_ns"), ("print",), ("trace",)}


_REGEX_CACHE: dict[str, "re.Pattern[str]"] = {}


def compiled_regex(pattern: str) -> "re.Pattern[str]":
    pat = _REGEX_CACHE.get(pattern)
    if pat is None:
        try:
            pat = re.compile(pattern)
        except re.error as e:
            raise BuiltinError(f"invalid regex {pattern!r}: {e}") from None
        _REGEX_CACHE[pattern] = pat
    return pat


def _need(v: Any, ty: str, fn: str) -> Any:
    if type_name(v) != ty:
        raise BuiltinError(f"{fn}: expected {ty}, got {type_name(v)}")
    return v


def _need_str(v: Any, fn: str) -> str:
    return _need(v, "string", fn)


def _need_num(v: Any, fn: str):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise BuiltinError(f"{fn}: expected number, got {type_name(v)}")
    return v


def _iterable(v: Any, fn: str):
    if isinstance(v, (tuple, frozenset)):
        return list(v)
    if isinstance(v, FrozenDict):
        return list(v.values())
    raise BuiltinError(f"{fn}: expected collection, got {type_name(v)}")


def bi_count(v):
    if isinstance(v, str):
        return len(v)
    if isinstance(v, (tuple, frozenset, FrozenDict)):
        return len(v)
    raise BuiltinError(f"count: cannot count {type_name(v)}")


def bi_to_number(v):
    if v is None:
        return 0
    if isinstance(v, bool):
        return 1 if v else 0
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                f = float(v)
            except ValueError:
                raise BuiltinError(f"to_number: invalid number {v!r}") from None
            return int(f) if f.is_integer() else f
    raise BuiltinError(f"to_number: cannot convert {type_name(v)}")


def bi_substring(s, start, length):
    s = _need_str(s, "substring")
    start = int(_need_num(start, "substring"))
    length = int(_need_num(length, "substring"))
    if start < 0:
        raise BuiltinError("substring: negative start")
    if length < 0:
        return s[start:]
    return s[start : start + length]


def bi_sprintf(fmt, args):
    fmt = _need_str(fmt, "sprintf")
    args = list(_need(args, "array", "sprintf"))
    out = []
    i, n = 0, len(fmt)
    ai = 0
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        if i + 1 < n and fmt[i + 1] == "%":
            out.append("%")
            i += 2
            continue
        # parse verb (with optional width/precision, which we pass through to %-style)
        j = i + 1
        while j < n and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= n:
            raise BuiltinError("sprintf: trailing %")
        verb = fmt[j]
        spec = fmt[i + 1 : j]
        if ai >= len(args):
            raise BuiltinError("sprintf: not enough arguments")
        arg = args[ai]
        ai += 1
        if verb == "v":
            out.append(format_value(arg, top=True))
        elif verb == "s":
            out.append(arg if isinstance(arg, str) else format_value(arg, top=True))
        elif verb in "dxXob":
            out.append(("%" + spec + verb) % int(_need_num(arg, "sprintf")))
        elif verb in "feEgG":
            out.append(("%" + spec + verb) % float(_need_num(arg, "sprintf")))
        else:
            raise BuiltinError(f"sprintf: unsupported verb %{verb}")
        i = j + 1
    return "".join(out)


def bi_min(coll):
    items = _iterable(coll, "min")
    if not items:
        raise BuiltinError("min: empty collection")
    return min(items, key=sort_key)


def bi_max(coll):
    items = _iterable(coll, "max")
    if not items:
        raise BuiltinError("max: empty collection")
    return max(items, key=sort_key)


def bi_trim(s, cutset):
    return _need_str(s, "trim").strip(_need_str(cutset, "trim"))


def bi_concat(delim, coll):
    delim = _need_str(delim, "concat")
    items = coll if isinstance(coll, tuple) else sorted(coll, key=sort_key) if isinstance(coll, frozenset) else None
    if items is None:
        raise BuiltinError("concat: expected array or set")
    for x in items:
        _need_str(x, "concat")
    return delim.join(items)


def bi_any(coll):
    items = _iterable(coll, "any")
    return any(x is True for x in items)


def bi_all(coll):
    items = _iterable(coll, "all")
    return all(x is True for x in items)


BUILTINS: dict[tuple, Any] = {
    ("count",): bi_count,
    ("to_number",): bi_to_number,
    ("substring",): bi_substring,
    ("sprintf",): bi_sprintf,
    ("min",): bi_min,
    ("max",): bi_max,
    ("sum",): lambda c: sum(_need_num(x, "sum") for x in _iterable(c, "sum")),
    ("product",): lambda c: __import__("math").prod(
        _need_num(x, "product") for x in _iterable(c, "product")
    ),
    ("any",): bi_any,
    ("all",): bi_all,
    ("trim",): bi_trim,
    ("trim_space",): lambda s: _need_str(s, "trim_space").strip(),
    ("concat",): bi_concat,
    ("split",): lambda s, d: tuple(
        _need_str(s, "split").split(_need_str(d, "split"))
    ),
    ("replace",): lambda s, o, nw: _need_str(s, "replace").replace(
        _need_str(o, "replace"), _need_str(nw, "replace")
    ),
    ("startswith",): lambda s, p: _need_str(s, "startswith").startswith(
        _need_str(p, "startswith")
    ),
    ("endswith",): lambda s, p: _need_str(s, "endswith").endswith(
        _need_str(p, "endswith")
    ),
    ("contains",): lambda s, p: _need_str(p, "contains") in _need_str(s, "contains"),
    ("indexof",): lambda s, p: _need_str(s, "indexof").find(_need_str(p, "indexof")),
    ("lower",): lambda s: _need_str(s, "lower").lower(),
    ("upper",): lambda s: _need_str(s, "upper").upper(),
    ("format_int",): lambda v, b: {2: "{:b}", 8: "{:o}", 10: "{:d}", 16: "{:x}"}[
        int(_need_num(b, "format_int"))
    ].format(int(_need_num(v, "format_int"))),
    ("abs",): lambda v: abs(_need_num(v, "abs")),
    ("round",): lambda v: int(round(_need_num(v, "round"))),
    ("sort",): lambda c: tuple(sorted(_iterable(c, "sort"), key=sort_key)),
    ("to_string",): lambda v: format_value(v, top=True),
    ("re_match",): lambda p, v: bool(
        compiled_regex(_need_str(p, "re_match")).search(_need_str(v, "re_match"))
    ),
    ("regex", "match"): lambda p, v: bool(
        compiled_regex(_need_str(p, "regex.match")).search(
            _need_str(v, "regex.match")
        )
    ),
    ("is_string",): lambda v: isinstance(v, str),
    ("is_number",): lambda v: not isinstance(v, bool) and isinstance(v, (int, float)),
    ("is_boolean",): lambda v: isinstance(v, bool),
    ("is_null",): lambda v: v is None,
    ("is_array",): lambda v: isinstance(v, tuple),
    ("is_object",): lambda v: isinstance(v, FrozenDict),
    ("is_set",): lambda v: isinstance(v, frozenset),
    ("array", "concat"): lambda a, b: _need(a, "array", "array.concat")
    + _need(b, "array", "array.concat"),
    ("array", "slice"): lambda a, i, j: _need(a, "array", "array.slice")[
        int(_need_num(i, "array.slice")) : int(_need_num(j, "array.slice"))
    ],
    ("object", "get"): lambda o, k, d: o.get(k, d)
    if isinstance(o, FrozenDict)
    else d,
    ("equal",): rego_eq,
    ("neq",): lambda a, b: not rego_eq(a, b),
    ("cast_array",): lambda v: tuple(v)
    if isinstance(v, (tuple, frozenset))
    else (_ for _ in ()).throw(BuiltinError("cast_array")),
    ("cast_string",): lambda v: _need_str(v, "cast_string"),
    ("cast_boolean",): lambda v: _need(v, "boolean", "cast_boolean"),
    # debugging no-ops (OPA topdown/trace.go): always true so bodies continue
    ("trace",): lambda *a: True,
    ("print",): lambda *a: True,
}


# ------------------------------------------------- breadth batch (r3)
# Builtins beyond the reference corpus' needs, for policy portability:
# the OPA v0.2x surface k8s policies most commonly reach for.


def _canon_json(v) -> str:
    """The one canonical JSON serialization (json.marshal, JWT signing
    payloads, http.send bodies) — a single definition so OPA-parity
    tweaks to number/key rendering can never diverge between them."""
    import json as _json

    from ..utils.values import thaw

    return _json.dumps(thaw(v), sort_keys=True, separators=(",", ":"))


def _bi_json_marshal(v):
    try:
        return _canon_json(v)
    except (TypeError, ValueError) as e:
        raise BuiltinError(f"json.marshal: {e}") from None


def _bi_json_unmarshal(s):
    import json as _json

    from ..utils.values import freeze

    try:
        return freeze(_json.loads(_need_str(s, "json.unmarshal")))
    except ValueError as e:
        raise BuiltinError(f"json.unmarshal: {e}") from None


def _b64(codec, name):
    import base64 as _b

    fn = getattr(_b, codec)

    def run(s):
        try:
            return fn(_need_str(s, name).encode()).decode()
        except Exception as e:  # noqa: BLE001
            raise BuiltinError(f"{name}: {e}") from None

    return run


def _b64dec(codec, name):
    import base64 as _b

    fn = getattr(_b, codec)

    def run(s):
        try:
            return fn(_need_str(s, name).encode()).decode()
        except Exception as e:  # noqa: BLE001
            raise BuiltinError(f"{name}: {e}") from None

    return run


def _bi_glob_match(pattern, delimiters, value):
    """OPA glob.match subset: *, **, ?, [classes], {alt,ernates};
    bare * and ? do not cross a delimiter (default ".")."""
    pattern = _need_str(pattern, "glob.match")
    value = _need_str(value, "glob.match")
    if delimiters is None:
        delims = ["."]
    else:
        delims = [_need_str(d, "glob.match")
                  for d in _iterable(delimiters, "glob.match")] or ["."]
    d = re.escape("".join(delims))
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
            else:
                out.append(f"[^{d}]*")
                i += 1
        elif c == "?":
            out.append(f"[^{d}]")
            i += 1
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j < 0:
                raise BuiltinError("glob.match: unterminated class")
            out.append(pattern[i:j + 1])
            i = j + 1
        elif c == "{":
            j = pattern.find("}", i + 1)
            if j < 0:
                raise BuiltinError("glob.match: unterminated alternates")
            alts = pattern[i + 1:j].split(",")
            out.append("(" + "|".join(re.escape(a) for a in alts) + ")")
            i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.fullmatch("".join(out), value) is not None


def _bi_numbers_range(a, b):
    lo = int(_need_num(a, "numbers.range"))
    hi = int(_need_num(b, "numbers.range"))
    step = 1 if hi >= lo else -1
    return tuple(range(lo, hi + step, step))


def _bi_union(sets):
    out: frozenset = frozenset()
    for s in _iterable(sets, "union"):
        out |= _need(s, "set", "union")
    return out


def _bi_intersection(sets):
    items = [_need(s, "set", "intersection")
             for s in _iterable(sets, "intersection")]
    if not items:
        return frozenset()
    out = items[0]
    for s in items[1:]:
        out &= s
    return out


def _trim_side(side):
    def run(s, cutset):
        v = _need_str(s, f"trim_{side}")
        cut = _need_str(cutset, f"trim_{side}")
        if not cut:
            return v
        return v.lstrip(cut) if side == "left" else v.rstrip(cut)

    return run


BUILTINS.update({
    ("json", "marshal"): _bi_json_marshal,
    ("json", "unmarshal"): _bi_json_unmarshal,
    ("base64", "encode"): _b64("b64encode", "base64.encode"),
    ("base64", "decode"): _b64dec("b64decode", "base64.decode"),
    ("base64url", "encode"): _b64("urlsafe_b64encode", "base64url.encode"),
    ("base64url", "decode"): _b64dec("urlsafe_b64decode",
                                     "base64url.decode"),
    ("glob", "match"): _bi_glob_match,
    ("numbers", "range"): _bi_numbers_range,
    ("union",): _bi_union,
    ("intersection",): _bi_intersection,
    ("type_name",): type_name,
    ("trim_left",): _trim_side("left"),
    ("trim_right",): _trim_side("right"),
    ("trim_prefix",): lambda s, p: _need_str(s, "trim_prefix")[
        len(_need_str(p, "trim_prefix")):]
    if _need_str(s, "trim_prefix").startswith(_need_str(p, "trim_prefix"))
    else _need_str(s, "trim_prefix"),
    ("trim_suffix",): lambda s, p: _need_str(s, "trim_suffix")[
        : len(_need_str(s, "trim_suffix")) - len(_need_str(p, "trim_suffix"))]
    if _need_str(p, "trim_suffix")
    and _need_str(s, "trim_suffix").endswith(_need_str(p, "trim_suffix"))
    else _need_str(s, "trim_suffix"),
})


# ---- breadth batch 2 (round 4): the commonly-used remainder of OPA's
# builtin surface (vendor/.../opa/topdown/*.go semantics; frozen values
# in and out, BuiltinError -> undefined)

import base64 as _base64
import binascii as _binascii
import json
import datetime as _dt
import hashlib as _hashlib
import hmac as _hmac_mod
import ipaddress as _ipaddress
import math as _math
import time as _time
import urllib.parse as _urlparse

from ..utils.values import freeze, thaw


def _bi_object_keys(o):
    _need(o, "object", "object.keys")
    return frozenset(o.keys())


def _bi_object_remove(o, ks):
    _need(o, "object", "object.remove")
    drop = set(_iterable(ks, "object.remove"))
    return FrozenDict((k, v) for k, v in o.items()
                      if not any(rego_eq(k, d) for d in drop))


def _bi_object_filter(o, ks):
    _need(o, "object", "object.filter")
    keep = set(_iterable(ks, "object.filter"))
    return FrozenDict((k, v) for k, v in o.items()
                      if any(rego_eq(k, d) for d in keep))


def _bi_object_union(a, b):
    _need(a, "object", "object.union")
    _need(b, "object", "object.union")

    def merge(x, y):
        if isinstance(x, FrozenDict) and isinstance(y, FrozenDict):
            out = dict(x)
            for k, v in y.items():
                out[k] = merge(out[k], v) if k in out else v
            return FrozenDict(out)
        return y

    return merge(a, b)


def _bi_object_union_n(objs):
    items = _iterable(objs, "object.union_n")
    out = FrozenDict()
    for o in items:
        out = _bi_object_union(out, _need(o, "object", "object.union_n"))
    return out


def _bi_regex_split(pattern, s):
    return tuple(compiled_regex(_need_str(pattern, "regex.split")).split(
        _need_str(s, "regex.split")))


def _bi_regex_is_valid(pattern):
    if not isinstance(pattern, str):
        return False
    try:
        re.compile(pattern)
        return True
    except re.error:
        return False


_GO_REF = re.compile(r"\$(\$|\{[A-Za-z0-9_]+\}|[A-Za-z0-9_]+)")


def _go_expand(template: str, m: "re.Match") -> str:
    """Go regexp.Expand: $1/${name} are submatch references; $$ is a
    literal $; unknown groups expand to the empty string."""
    def ref(rm):
        name = rm.group(1)
        if name == "$":
            return "$"
        if name.startswith("{"):
            name = name[1:-1]
        try:
            if name.isdigit():
                idx = int(name)
                if idx > m.re.groups:
                    return ""
                return m.group(idx) or ""
            return m.group(name) or ""
        except IndexError:  # unknown group: empty (Go Expand)
            return ""
    return _GO_REF.sub(ref, template)


def _bi_regex_replace(s, pattern, value):
    pat = compiled_regex(_need_str(pattern, "regex.replace"))
    tmpl = _need_str(value, "regex.replace")
    return pat.sub(lambda m: _go_expand(tmpl, m),
                   _need_str(s, "regex.replace"))


def _bi_regex_find_n(pattern, s, n):
    pat = compiled_regex(_need_str(pattern, "regex.find_n"))
    cnt = int(_need_num(n, "regex.find_n"))
    out = [m.group(0) for m in pat.finditer(_need_str(s, "regex.find_n"))]
    return tuple(out if cnt < 0 else out[:cnt])


def _bi_strings_reverse(s):
    return _need_str(s, "strings.reverse")[::-1]


def _bi_strings_count(s, sub):
    return _need_str(s, "strings.count").count(
        _need_str(sub, "strings.count"))


def _bi_indexof_n(s, sub):
    h = _need_str(s, "indexof_n")
    n = _need_str(sub, "indexof_n")
    out, i = [], h.find(n)
    while i != -1:
        out.append(i)
        i = h.find(n, i + 1)
    return tuple(out)


def _bi_replace_n(patterns, s):
    _need(patterns, "object", "strings.replace_n")
    out = _need_str(s, "strings.replace_n")
    for old, new in patterns.items():
        out = out.replace(_need_str(old, "strings.replace_n"),
                          _need_str(new, "strings.replace_n"))
    return out


def _bi_any_prefix_match(search, base):
    ss = [search] if isinstance(search, str) else \
        _iterable(search, "strings.any_prefix_match")
    bs = [base] if isinstance(base, str) else \
        _iterable(base, "strings.any_prefix_match")
    return any(_need_str(s, "strings.any_prefix_match").startswith(
        _need_str(b, "strings.any_prefix_match")) for s in ss for b in bs)


def _bi_any_suffix_match(search, base):
    ss = [search] if isinstance(search, str) else \
        _iterable(search, "strings.any_suffix_match")
    bs = [base] if isinstance(base, str) else \
        _iterable(base, "strings.any_suffix_match")
    return any(_need_str(s, "strings.any_suffix_match").endswith(
        _need_str(b, "strings.any_suffix_match")) for s in ss for b in bs)


def _bi_hex_encode(s):
    return _need_str(s, "hex.encode").encode().hex()


def _bi_hex_decode(s):
    try:
        return bytes.fromhex(_need_str(s, "hex.decode")).decode()
    except (ValueError, UnicodeDecodeError) as e:
        raise BuiltinError(f"hex.decode: {e}") from None


def _bi_urlquery_encode(s):
    return _urlparse.quote_plus(_need_str(s, "urlquery.encode"))


def _bi_urlquery_decode(s):
    return _urlparse.unquote_plus(_need_str(s, "urlquery.decode"))


def _bi_urlquery_encode_object(o):
    _need(o, "object", "urlquery.encode_object")
    parts = []
    for k, v in o.items():
        key = _urlparse.quote_plus(_need_str(k, "urlquery.encode_object"))
        vals = [v] if isinstance(v, str) else \
            _iterable(v, "urlquery.encode_object")
        for x in vals:
            parts.append(f"{key}="
                         f"{_urlparse.quote_plus(_need_str(x, 'urlquery'))}")
    return "&".join(parts)


def _bi_urlquery_decode_object(s):
    parsed = _urlparse.parse_qs(_need_str(s, "urlquery.decode_object"),
                                keep_blank_values=True)
    return FrozenDict((k, tuple(v)) for k, v in parsed.items())


def _bi_json_is_valid(s):
    if not isinstance(s, str):
        return False
    try:
        json.loads(s)
        return True
    except ValueError:
        return False


def _bi_yaml_marshal(v):
    import yaml as _yaml
    return _yaml.safe_dump(thaw(v), default_flow_style=False)


def _bi_yaml_unmarshal(s):
    import yaml as _yaml
    try:
        return freeze(_yaml.safe_load(_need_str(s, "yaml.unmarshal")))
    except _yaml.YAMLError as e:
        raise BuiltinError(f"yaml.unmarshal: {e}") from None


def _bi_yaml_is_valid(s):
    import yaml as _yaml
    if not isinstance(s, str):
        return False
    try:
        _yaml.safe_load(s)
        return True
    except _yaml.YAMLError:
        return False


def _bi_base64_is_valid(s):
    if not isinstance(s, str):
        return False
    try:
        _base64.b64decode(s, validate=True)
        return True
    except (_binascii.Error, ValueError):
        return False


def _hash(algo):
    def run(s):
        return getattr(_hashlib, algo)(
            _need_str(s, f"crypto.{algo}").encode()).hexdigest()
    return run


def _hmac(algo):
    def run(s, key):
        return _hmac_new(_need_str(key, f"crypto.hmac.{algo}"),
                         _need_str(s, f"crypto.hmac.{algo}"), algo)
    return run


def _hmac_new(key: str, msg: str, algo: str) -> str:
    return _hmac_mod.new(key.encode(), msg.encode(),
                         getattr(_hashlib, algo)).hexdigest()


def _bi_ceil(x):
    return int(_math.ceil(_need_num(x, "ceil")))


def _bi_floor(x):
    return int(_math.floor(_need_num(x, "floor")))


def _bi_numbers_range_step(a, b, step):
    lo = _need_num(a, "numbers.range_step")
    hi = _need_num(b, "numbers.range_step")
    st = _need_num(step, "numbers.range_step")
    if not float(st).is_integer() or st <= 0:
        raise BuiltinError("numbers.range_step: step must be a positive "
                           "integer")
    st = int(st)
    if lo <= hi:
        return tuple(range(int(lo), int(hi) + 1, st))
    return tuple(range(int(lo), int(hi) - 1, -st))


def _bi_array_reverse(a):
    _need(a, "array", "array.reverse")
    return tuple(reversed(a))


def _bi_time_now_ns():
    return int(_time.time() * 1e9)


_FRAC_RE = re.compile(r"\.(\d+)")


def _bi_parse_rfc3339_ns(s):
    v = _need_str(s, "time.parse_rfc3339_ns")
    try:
        if v.endswith("Z"):
            v = v[:-1] + "+00:00"
        frac_ns = 0
        fm = _FRAC_RE.search(v)
        if fm:
            digits = fm.group(1)[:9]
            frac_ns = int(digits.ljust(9, "0"))
            v = v[: fm.start()] + v[fm.end():]
        dt = _dt.datetime.fromisoformat(v)
    except ValueError as e:
        raise BuiltinError(f"time.parse_rfc3339_ns: {e}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp()) * 10**9 + frac_ns


def _ns_split(ns) -> tuple["_dt.datetime", int]:
    """(civil datetime of the whole seconds, sub-second ns) — carrying
    the remainder separately keeps builtins nanosecond-exact (OPA's
    topdown is; rounding through datetime.microsecond loses sub-us)."""
    s, rem = divmod(int(_need_num(ns, "time")), 10**9)
    return _dt.datetime.fromtimestamp(s, tz=_dt.timezone.utc), rem


def _ns_to_dt(ns) -> "_dt.datetime":
    d, rem = _ns_split(ns)
    return d.replace(microsecond=rem // 1000)


def _bi_time_date(ns):
    d = _ns_to_dt(ns)
    return (d.year, d.month, d.day)


def _bi_time_clock(ns):
    d = _ns_to_dt(ns)
    return (d.hour, d.minute, d.second)


def _bi_time_weekday(ns):
    return _ns_to_dt(ns).strftime("%A")


def _bi_time_add_date(ns, years, months, days):
    d, sub_ns = _ns_split(ns)
    y = int(_need_num(years, "time.add_date"))
    mo = int(_need_num(months, "time.add_date"))
    dd = int(_need_num(days, "time.add_date"))
    month0 = d.month - 1 + mo
    year = d.year + y + month0 // 12
    month = month0 % 12 + 1
    # Go's AddDate normalizes out-of-range days by rolling over
    day = d.day
    base = _dt.datetime(year, month, 1, d.hour, d.minute, d.second,
                        tzinfo=_dt.timezone.utc)
    out = base + _dt.timedelta(days=day - 1 + dd)
    # the sub-second ns ride through untouched (ns-exact like topdown)
    return int(out.timestamp()) * 10**9 + sub_ns


_UNITS = {"": 1, "k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12,
          "p": 10**15, "e": 10**18,
          "ki": 2**10, "mi": 2**20, "gi": 2**30, "ti": 2**40,
          "pi": 2**50, "ei": 2**60}


def _parse_units(s: str, fn: str, milli_ok: bool, bytes_ok: bool):
    v = _need_str(s, fn).strip().strip('"')
    if not v:
        raise BuiltinError(f"{fn}: no amount provided")
    i = len(v)
    while i > 0 and not (v[i - 1].isdigit() or v[i - 1] == "."):
        i -= 1
    num, raw = v[:i], v[i:]
    if not num:
        raise BuiltinError(f"{fn}: no amount provided")
    try:
        base = float(num) if "." in num else int(num)
    except ValueError as e:
        raise BuiltinError(f"{fn}: {e}") from None
    if milli_ok and raw == "m":  # case-sensitive: 'M' is mega, 'm' milli
        return base / 1000
    suffix = raw.lower()
    if bytes_ok:  # only parse_bytes accepts b/KB/KiB spellings
        if suffix == "b":
            suffix = ""
        elif suffix.endswith("b") and suffix[:-1] in _UNITS:
            suffix = suffix[:-1]
    if suffix not in _UNITS:
        raise BuiltinError(f"{fn}: unknown unit suffix {raw!r}")
    out = base * _UNITS[suffix]
    return int(out) if float(out).is_integer() else out


def _bi_units_parse(s):
    # decimal k/M/G... and binary Ki/Mi/Gi... (no bytes 'b' suffix)
    return _parse_units(s, "units.parse", milli_ok=True, bytes_ok=False)


def _bi_units_parse_bytes(s):
    return int(_parse_units(s, "units.parse_bytes", milli_ok=False,
                            bytes_ok=True))


def _net(v, fn):
    try:
        s = _need_str(v, fn)
        if "/" in s:
            return _ipaddress.ip_network(s, strict=False)
        return _ipaddress.ip_network(s + "/32" if ":" not in s
                                     else s + "/128", strict=False)
    except ValueError as e:
        raise BuiltinError(f"{fn}: {e}") from None


def _bi_cidr_contains(cidr, x):
    net = _net(cidr, "net.cidr_contains")
    other = _net(x, "net.cidr_contains")
    try:
        return other.subnet_of(net)
    except TypeError as e:  # mixed IPv4/IPv6: undefined, not a crash
        raise BuiltinError(f"net.cidr_contains: {e}") from None


def _bi_cidr_intersects(a, b):
    try:
        return _net(a, "net.cidr_intersects").overlaps(
            _net(b, "net.cidr_intersects"))
    except TypeError as e:
        raise BuiltinError(f"net.cidr_intersects: {e}") from None


def _bi_cidr_is_valid(v):
    if not isinstance(v, str):
        return False
    try:
        _ipaddress.ip_network(v, strict=False)
        return True
    except ValueError:
        return False


_SEMVER = re.compile(
    r"^(\d+)\.(\d+)\.(\d+)(?:-([0-9A-Za-z.-]+))?(?:\+[0-9A-Za-z.-]+)?$")


def _semver_key(v: str, fn: str):
    m = _SEMVER.match(_need_str(v, fn))
    if not m:
        raise BuiltinError(f"{fn}: invalid semver {v!r}")
    major, minor, patch = int(m.group(1)), int(m.group(2)), int(m.group(3))
    pre = m.group(4)
    if pre is None:
        pre_key = (1,)  # releases sort after any pre-release
    else:
        parts = []
        for p in pre.split("."):
            parts.append((0, int(p)) if p.isdigit() else (1, p))
        pre_key = (0, tuple(parts))
    return (major, minor, patch, pre_key)


def _bi_semver_is_valid(v):
    return isinstance(v, str) and bool(_SEMVER.match(v))


def _bi_semver_compare(a, b):
    ka = _semver_key(a, "semver.compare")
    kb = _semver_key(b, "semver.compare")
    return -1 if ka < kb else (1 if ka > kb else 0)


def _bits(fn_name, op):
    def run(a, b):
        x = _need_num(a, fn_name)
        y = _need_num(b, fn_name)
        if not float(x).is_integer() or not float(y).is_integer():
            raise BuiltinError(f"{fn_name}: operands must be integers")
        return op(int(x), int(y))
    return run


BUILTINS.update({
    ("object", "keys"): _bi_object_keys,
    ("object", "remove"): _bi_object_remove,
    ("object", "filter"): _bi_object_filter,
    ("object", "union"): _bi_object_union,
    ("object", "union_n"): _bi_object_union_n,
    ("regex", "split"): _bi_regex_split,
    ("regex", "is_valid"): _bi_regex_is_valid,
    ("regex", "replace"): _bi_regex_replace,
    ("regex", "find_n"): _bi_regex_find_n,
    ("strings", "reverse"): _bi_strings_reverse,
    ("strings", "count"): _bi_strings_count,
    ("strings", "replace_n"): _bi_replace_n,
    ("strings", "any_prefix_match"): _bi_any_prefix_match,
    ("strings", "any_suffix_match"): _bi_any_suffix_match,
    ("indexof_n",): _bi_indexof_n,
    ("hex", "encode"): _bi_hex_encode,
    ("hex", "decode"): _bi_hex_decode,
    ("urlquery", "encode"): _bi_urlquery_encode,
    ("urlquery", "decode"): _bi_urlquery_decode,
    ("urlquery", "encode_object"): _bi_urlquery_encode_object,
    ("urlquery", "decode_object"): _bi_urlquery_decode_object,
    ("json", "is_valid"): _bi_json_is_valid,
    ("yaml", "marshal"): _bi_yaml_marshal,
    ("yaml", "unmarshal"): _bi_yaml_unmarshal,
    ("yaml", "is_valid"): _bi_yaml_is_valid,
    ("base64", "is_valid"): _bi_base64_is_valid,
    ("crypto", "md5"): _hash("md5"),
    ("crypto", "sha1"): _hash("sha1"),
    ("crypto", "sha256"): _hash("sha256"),
    ("crypto", "hmac", "md5"): _hmac("md5"),
    ("crypto", "hmac", "sha1"): _hmac("sha1"),
    ("crypto", "hmac", "sha256"): _hmac("sha256"),
    ("crypto", "hmac", "sha512"): _hmac("sha512"),
    ("crypto", "hmac", "equal"): lambda a, b: _hmac_mod.compare_digest(
        _need_str(a, "crypto.hmac.equal"), _need_str(b, "crypto.hmac.equal")),
    ("ceil",): _bi_ceil,
    ("floor",): _bi_floor,
    ("numbers", "range_step"): _bi_numbers_range_step,
    ("array", "reverse"): _bi_array_reverse,
    ("time", "now_ns"): _bi_time_now_ns,
    ("time", "parse_rfc3339_ns"): _bi_parse_rfc3339_ns,
    ("time", "date"): _bi_time_date,
    ("time", "clock"): _bi_time_clock,
    ("time", "weekday"): _bi_time_weekday,
    ("time", "add_date"): _bi_time_add_date,
    ("units", "parse"): _bi_units_parse,
    ("units", "parse_bytes"): _bi_units_parse_bytes,
    ("net", "cidr_contains"): _bi_cidr_contains,
    ("net", "cidr_intersects"): _bi_cidr_intersects,
    ("net", "cidr_is_valid"): _bi_cidr_is_valid,
    ("semver", "is_valid"): _bi_semver_is_valid,
    ("semver", "compare"): _bi_semver_compare,
    ("bits", "or"): _bits("bits.or", lambda a, b: a | b),
    ("bits", "and"): _bits("bits.and", lambda a, b: a & b),
    ("bits", "xor"): _bits("bits.xor", lambda a, b: a ^ b),
    ("bits", "lsh"): _bits("bits.lsh", lambda a, b: a << b),
    ("bits", "rsh"): _bits("bits.rsh", lambda a, b: a >> b),
    ("bits", "negate"): lambda a: ~int(_need_num(a, "bits.negate")),
})


# ---- breadth batch 3: json document surgery, graph traversal, jwt ----


def _split_json_path(p, fn: str):
    if isinstance(p, str):
        return tuple(seg for seg in p.split("/") if seg != "")
    if isinstance(p, tuple):
        return tuple(str(x) if not isinstance(x, str) else x for x in p)
    raise BuiltinError(f"{fn}: path must be a string or array")


def _paths_trie(paths, fn: str):
    trie: dict = {}
    for p in _iterable(paths, fn):
        node = trie
        for seg in _split_json_path(p, fn):
            node = node.setdefault(seg, {})
        node["\x00end"] = True
    return trie


def _step_into(v, seg: str):
    if isinstance(v, FrozenDict):
        if seg in v:
            return True, v[seg]
        return False, None
    if isinstance(v, tuple):
        try:
            i = int(seg)
        except ValueError:
            return False, None
        if 0 <= i < len(v):
            return True, v[i]
    return False, None


def _bi_json_filter(obj, paths):
    """Keep only the listed paths (OPA topdown/json.go Filter)."""
    _need(obj, "object", "json.filter")
    trie = _paths_trie(paths, "json.filter")

    def keep(v, node):
        if "\x00end" in node:
            return v
        if isinstance(v, FrozenDict):
            out = {}
            for k, child in node.items():
                if k == "\x00end":
                    continue
                present, sub = _step_into(v, k)
                if present:
                    kept = keep(sub, child)
                    if kept is not _MISSING_JSON:
                        out[k] = kept
            return FrozenDict(out)
        if isinstance(v, tuple):
            out = []
            # original index order, not trie insertion order
            for k, child in sorted(
                    ((k, c) for k, c in node.items() if k != "\x00end"),
                    key=lambda kv: int(kv[0]) if kv[0].isdigit() else 0):
                present, sub = _step_into(v, k)
                if present:
                    kept = keep(sub, child)
                    if kept is not _MISSING_JSON:
                        out.append(kept)
            return tuple(out)
        return _MISSING_JSON

    got = keep(obj, trie)
    return got if got is not _MISSING_JSON else FrozenDict()


_MISSING_JSON = object()


def _bi_json_remove(obj, paths):
    """Remove the listed paths (OPA topdown/json.go Remove)."""
    _need(obj, "object", "json.remove")
    trie = _paths_trie(paths, "json.remove")

    def strip(v, node):
        if "\x00end" in node:
            return _MISSING_JSON
        if isinstance(v, FrozenDict):
            out = {}
            for k, sub in v.items():
                child = node.get(k if isinstance(k, str) else str(k))
                if child is None:
                    out[k] = sub
                else:
                    kept = strip(sub, child)
                    if kept is not _MISSING_JSON:
                        out[k] = kept
            return FrozenDict(out)
        if isinstance(v, tuple):
            out = []
            for i, sub in enumerate(v):
                child = node.get(str(i))
                if child is None:
                    out.append(sub)
                else:
                    kept = strip(sub, child)
                    if kept is not _MISSING_JSON:
                        out.append(kept)
            return tuple(out)
        return v

    got = strip(obj, trie)
    return got if got is not _MISSING_JSON else FrozenDict()


def _bi_object_subset(sup, sub):
    """True when sub is a (recursive) subset of sup: objects by keys,
    sets by membership, arrays by subsequence (OPA object.subset)."""
    def check(a, b):
        if isinstance(b, FrozenDict) and isinstance(a, FrozenDict):
            return all(k in a and check(a[k], v) for k, v in b.items())
        if isinstance(b, frozenset) and isinstance(a, frozenset):
            return b <= a
        if isinstance(b, tuple) and isinstance(a, tuple):
            i = 0
            for x in a:
                if i < len(b) and rego_eq(x, b[i]):
                    i += 1
            return i == len(b)
        return rego_eq(a, b)

    return check(sup, sub)


def _bi_graph_reachable(graph, initial):
    """Node set reachable from `initial` over an adjacency object whose
    values are arrays/sets of neighbor keys (OPA graph.reachable)."""
    _need(graph, "object", "graph.reachable")
    frontier = list(_iterable(initial, "graph.reachable"))
    seen = set()
    while frontier:
        n = frontier.pop()
        if n in seen:
            continue
        seen.add(n)
        nbrs = graph.get(n)
        if isinstance(nbrs, (tuple, frozenset)):
            frontier.extend(nbrs)
    return frozenset(seen)


def _b64url_decode_pad(s: str, fn: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    try:
        return _base64.urlsafe_b64decode(s + pad)
    except (_binascii.Error, ValueError) as e:
        raise BuiltinError(f"{fn}: {e}") from None


def _bi_jwt_decode(token):
    """[header, payload, signature-hex] without verification (OPA
    io.jwt.decode, topdown/tokens.go)."""
    parts = _need_str(token, "io.jwt.decode").split(".")
    if len(parts) != 3:
        raise BuiltinError("io.jwt.decode: expected 3 '.'-separated parts")
    try:
        header = json.loads(_b64url_decode_pad(parts[0], "io.jwt.decode"))
        payload = json.loads(_b64url_decode_pad(parts[1], "io.jwt.decode"))
    except ValueError as e:
        raise BuiltinError(f"io.jwt.decode: {e}") from None
    sig = _b64url_decode_pad(parts[2], "io.jwt.decode").hex()
    return (freeze(header), freeze(payload), sig)


_HS_DIGESTS = {"HS256": _hashlib.sha256, "HS384": _hashlib.sha384,
               "HS512": _hashlib.sha512}


def _jwt_verify_hs(token, secret, algo: str) -> bool:
    fn = f"io.jwt.verify_{algo.lower()}"
    parts = _need_str(token, fn).split(".")
    if len(parts) != 3:
        return False
    mac = _hmac_mod.new(_need_str(secret, fn).encode(),
                        f"{parts[0]}.{parts[1]}".encode(),
                        _HS_DIGESTS[algo]).digest()
    return _hmac_mod.compare_digest(
        mac, _b64url_decode_pad(parts[2], fn))


def _bi_jwt_verify_hs256(token, secret):
    return _jwt_verify_hs(token, secret, "HS256")


BUILTINS.update({
    ("json", "filter"): _bi_json_filter,
    ("json", "remove"): _bi_json_remove,
    ("object", "subset"): _bi_object_subset,
    ("graph", "reachable"): _bi_graph_reachable,
    ("io", "jwt", "decode"): _bi_jwt_decode,
    ("io", "jwt", "verify_hs256"): _bi_jwt_verify_hs256,
    ("base64url", "encode_no_pad"): lambda s: _base64.urlsafe_b64encode(
        _need_str(s, "base64url.encode_no_pad").encode()
    ).decode().rstrip("="),
})


def _json_ptr(path: str, fn: str) -> list:
    if path == "":
        return []
    if not path.startswith("/"):
        raise BuiltinError(f"{fn}: path must start with '/'")
    return [seg.replace("~1", "/").replace("~0", "~")
            for seg in path.split("/")[1:]]


def _patch_apply(doc, segs: list, op: str, value, fn: str):
    """Immutable RFC 6902 add/remove/replace on frozen values."""
    if not segs:
        if op == "remove":
            raise BuiltinError(f"{fn}: cannot remove the root")
        return value
    seg = segs[0]
    if isinstance(doc, FrozenDict):
        if len(segs) == 1:
            d = dict(doc)
            if op == "remove":
                if seg not in d:
                    raise BuiltinError(f"{fn}: path not found: {seg}")
                d.pop(seg)
            elif op == "replace":
                if seg not in d:
                    raise BuiltinError(f"{fn}: path not found: {seg}")
                d[seg] = value
            else:  # add
                d[seg] = value
            return FrozenDict(d)
        if seg not in doc:
            raise BuiltinError(f"{fn}: path not found: {seg}")
        d = dict(doc)
        d[seg] = _patch_apply(doc[seg], segs[1:], op, value, fn)
        return FrozenDict(d)
    if isinstance(doc, tuple):
        if seg == "-" and op == "add" and len(segs) == 1:
            return doc + (value,)
        try:
            i = int(seg)
        except ValueError:
            raise BuiltinError(f"{fn}: bad array index {seg!r}") from None
        if not (0 <= i <= len(doc) - (0 if op == "add" else 1)):
            raise BuiltinError(f"{fn}: index {i} out of range")
        if len(segs) == 1:
            if op == "add":
                return doc[:i] + (value,) + doc[i:]
            if op == "remove":
                return doc[:i] + doc[i + 1:]
            return doc[:i] + (value,) + doc[i + 1:]
        return doc[:i] + (_patch_apply(doc[i], segs[1:], op, value, fn),) \
            + doc[i + 1:]
    raise BuiltinError(f"{fn}: cannot descend into {type_name(doc)}")


def _bi_json_patch(doc, patches):
    """RFC 6902 add/remove/replace/copy/move/test (OPA json.patch)."""
    fn = "json.patch"
    out = doc
    for p in _iterable(patches, fn):
        _need(p, "object", fn)
        op = p.get("op")
        path = _json_ptr(_need_str(p.get("path", ""), fn), fn)
        if op in ("add", "replace"):
            out = _patch_apply(out, path, op, p.get("value"), fn)
        elif op == "remove":
            out = _patch_apply(out, path, "remove", None, fn)
        elif op in ("copy", "move"):
            src = _json_ptr(_need_str(p.get("from", ""), fn), fn)
            node = out
            for seg in src:
                present, node = _step_into(node, seg)
                if not present:
                    raise BuiltinError(f"{fn}: from path not found")
            if op == "move":
                out = _patch_apply(out, src, "remove", None, fn)
            out = _patch_apply(out, path, "add", node, fn)
        elif op == "test":
            node = out
            for seg in path:
                present, node = _step_into(node, seg)
                if not present:
                    raise BuiltinError(f"{fn}: test path not found")
            if not rego_eq(node, p.get("value")):
                raise BuiltinError(f"{fn}: test failed")
        else:
            raise BuiltinError(f"{fn}: unsupported op {op!r}")
    return out


def _bi_time_diff(a, b):
    """[years, months, days, hours, minutes, seconds] between two ns
    timestamps (OPA time.diff, Go-style civil difference)."""
    d1 = _ns_to_dt(a)
    d2 = _ns_to_dt(b)
    if d1 < d2:
        d1, d2 = d2, d1
    y = d1.year - d2.year
    mo = d1.month - d2.month
    dd = d1.day - d2.day
    hh = d1.hour - d2.hour
    mi = d1.minute - d2.minute
    ss = d1.second - d2.second
    if ss < 0:
        ss += 60
        mi -= 1
    if mi < 0:
        mi += 60
        hh -= 1
    if hh < 0:
        hh += 24
        dd -= 1
    if dd < 0:
        prev_month_year = d1.year if d1.month > 1 else d1.year - 1
        prev_month = d1.month - 1 if d1.month > 1 else 12
        import calendar as _cal
        dd += _cal.monthrange(prev_month_year, prev_month)[1]
        mo -= 1
    if mo < 0:
        mo += 12
        y -= 1
    return (y, mo, dd, hh, mi, ss)


BUILTINS.update({
    ("json", "patch"): _bi_json_patch,
    ("time", "diff"): _bi_time_diff,
})


# --------------------------------------------------------------- round 5
# The builtin tail to OPA parity (reference vendor/.../topdown/
# {crypto,tokens,time,cidr,regex,http}.go): x509/jwt asymmetric
# verification, Go-layout time parsing/formatting, the cidr tail, regex
# template/glob matching, gated http.send, and the named forms of the
# infix operators (callable in OPA: plus(1, 2, x)).


# offset-token render kinds: how Go prints the zone for each layout token
_TZ_TOKENS = [("Z07:00", "zcolon"), ("Z0700", "znum"),
              ("-07:00", "colon"), ("-0700", "num"), ("-07", "hour")]

# format-mode placeholders: strftime passes unknown bytes through, so
# fraction/offset render manually afterwards (ns-exact, Go-style)
_FRAC_MARK = "\x01"
_TZ_MARK = "\x02"


def _go_layout_convert(layout: str, fn: str, formatting: bool):
    """Go reference-time layout -> strftime/strptime format.

    Parse mode: offset tokens map to %z, fraction runs are dropped
    (the caller extracts fractional digits from the value for ns
    exactness). Format mode: fraction and offset become placeholder
    marks rendered manually by _bi_time_format. Returns
    (fmt, fraction (char, width) or None, tz_kind or None)."""
    tokens = [
        ("2006", "%Y"), ("January", "%B"), ("Monday", "%A"),
        ("Jan", "%b"), ("Mon", "%a"), ("15", "%H"), ("01", "%m"),
        ("02", "%d"), ("03", "%I"), ("04", "%M"), ("05", "%S"),
        ("06", "%y"), ("PM", "%p"), ("pm", "%p"), ("MST", "%Z"),
    ]
    out = []
    i = 0
    fraction = None
    tz_kind = None
    n = len(layout)
    while i < n:
        if layout[i] == "." and i + 1 < n and layout[i + 1] in "09":
            c = layout[i + 1]
            j = i + 1
            while j < n and layout[j] == c:
                j += 1
            # Go's nextStdChunk: a fractional second only when the digit
            # run ends the digit string — ".0" in "2006.01.02" is a
            # literal dot before the std01 month token, not a fraction
            if j >= n or layout[j] not in "0123456789":
                fraction = (c, j - i - 1)
                if formatting:
                    out.append(_FRAC_MARK)
                i = j
                continue
        matched = False
        for tok, kind in _TZ_TOKENS:
            if layout.startswith(tok, i):
                tz_kind = kind
                out.append(_TZ_MARK if formatting else "%z")
                i += len(tok)
                matched = True
                break
        if matched:
            continue
        for tok, fmt in tokens:
            if layout.startswith(tok, i):
                out.append(fmt)
                i += len(tok)
                break
        else:
            ch = layout[i]
            out.append("%%" if ch == "%" else ch)
            i += 1
    return "".join(out), fraction, tz_kind


def _bi_time_parse_ns(layout, value):
    """Go time.Parse semantics for the common layout tokens
    (topdown/time.go builtinParseNanos); ns-exact."""
    lay = _need_str(layout, "time.parse_ns")
    v = _need_str(value, "time.parse_ns")
    fmt, fraction, _tz = _go_layout_convert(lay, "time.parse_ns",
                                            formatting=False)
    frac_ns = 0
    if fraction is not None:
        fm = _FRAC_RE.search(v)
        if fm:
            digits = fm.group(1)[:9]
            frac_ns = int(digits.ljust(9, "0"))
            v = v[: fm.start()] + v[fm.end():]
    try:
        d = _dt.datetime.strptime(v, fmt)
    except ValueError as e:
        raise BuiltinError(f"time.parse_ns: {e}") from None
    if d.tzinfo is None:
        d = d.replace(tzinfo=_dt.timezone.utc)
    return int(d.timestamp()) * 10**9 + frac_ns


_DUR_RE = re.compile(r"([0-9]*\.?[0-9]+)(ns|us|µs|μs|ms|s|m|h)")
_DUR_NS = {"ns": 1, "us": 10**3, "µs": 10**3, "μs": 10**3,
           "ms": 10**6, "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9}


def _bi_time_parse_duration_ns(s):
    """Go time.ParseDuration ("1h30m", "-2.5s", ...) -> ns."""
    v = _need_str(s, "time.parse_duration_ns").strip()
    sign = 1
    if v.startswith(("-", "+")):
        sign = -1 if v[0] == "-" else 1
        v = v[1:]
    if v == "0":
        return 0
    total = 0
    pos = 0
    for m in _DUR_RE.finditer(v):
        if m.start() != pos:
            raise BuiltinError(
                f"time.parse_duration_ns: invalid duration {s!r}")
        total += int(float(m.group(1)) * _DUR_NS[m.group(2)])
        pos = m.end()
    if pos != len(v) or pos == 0:
        raise BuiltinError(f"time.parse_duration_ns: invalid duration {s!r}")
    return sign * total


def _bi_time_format(x):
    """ns | [ns, tz] | [ns, tz, go-layout] -> formatted string
    (modern-OPA time.format; the vendored version predates it)."""
    lay = "2006-01-02T15:04:05Z07:00"  # RFC3339
    tz = "UTC"
    if isinstance(x, tuple):
        if not x:
            raise BuiltinError("time.format: empty array")
        ns = x[0]
        if len(x) > 1:
            tz = _need_str(x[1], "time.format") or "UTC"
        if len(x) > 2:
            lay = _need_str(x[2], "time.format")
    else:
        ns = x
    d, sub = _ns_split(ns)
    if tz not in ("UTC", ""):
        if tz == "Local":
            d = d.astimezone()
        else:
            try:
                import zoneinfo
                d = d.astimezone(zoneinfo.ZoneInfo(tz))
            except Exception as e:
                raise BuiltinError(f"time.format: {e}") from None
    fmt, fraction, tz_kind = _go_layout_convert(lay, "time.format",
                                                formatting=True)
    out = d.strftime(fmt)
    if fraction is not None:
        c, width = fraction
        if c == "0":  # fixed width, trailing zeros kept
            frac = "." + f"{sub:09d}"[:width].ljust(width, "0")
        else:  # '9': trailing zeros (and a bare '.') dropped
            frac = ("." + f"{sub:09d}"[:width]).rstrip("0").rstrip(".")
        out = out.replace(_FRAC_MARK, frac)
    if tz_kind is not None:
        off = d.utcoffset() or _dt.timedelta(0)
        total = int(off.total_seconds())
        sign = "-" if total < 0 else "+"
        hh, mm = divmod(abs(total) // 60, 60)
        if tz_kind in ("zcolon", "znum") and total == 0:
            zs = "Z"
        elif tz_kind in ("zcolon", "colon"):
            zs = f"{sign}{hh:02d}:{mm:02d}"
        elif tz_kind == "hour":
            zs = f"{sign}{hh:02d}"
        else:
            zs = f"{sign}{hh:02d}{mm:02d}"
        out = out.replace(_TZ_MARK, zs)
    return out


def _bi_cidr_expand(cidr):
    try:
        net = _ipaddress.ip_network(_need_str(cidr, "net.cidr_expand"),
                                    strict=False)
    except ValueError as e:
        raise BuiltinError(f"net.cidr_expand: {e}") from None
    if net.num_addresses > (1 << 20):
        raise BuiltinError(
            f"net.cidr_expand: {cidr} expands to {net.num_addresses} "
            "addresses (limit 2^20)")
    return frozenset(str(ip) for ip in net)


def _bi_cidr_merge(addrs):
    nets4, nets6 = [], []
    for a in _iterable(addrs, "net.cidr_merge"):
        n = _net(a, "net.cidr_merge")
        (nets4 if n.version == 4 else nets6).append(n)
    out = []
    for group in (nets4, nets6):
        out.extend(_ipaddress.collapse_addresses(group))
    return frozenset(str(n) for n in out)


def _cidr_contains_pair(cidr, x, fn):
    a = _net(cidr, fn)
    b = _net(x, fn)
    if a.version != b.version:
        return False
    return b.network_address >= a.network_address and \
        b.broadcast_address <= a.broadcast_address


def _cidr_match_iter(operand, v, fn):
    """(cidr, index) pairs per topdown/cidr.go
    evalNetCIDRContainsMatchesOperand: string -> itself; array -> first
    element of each entry, integer index; set -> member as index;
    object -> value's cidr, key as index."""
    def term(x):
        if isinstance(x, str):
            return x
        if isinstance(x, tuple) and x:
            return x[0]
        raise BuiltinError(
            f"{fn}: operand {operand}: element must be string or "
            "non-empty array")

    if isinstance(v, str):
        yield v, v
    elif isinstance(v, tuple):
        for i, x in enumerate(v):
            yield term(x), i
    elif isinstance(v, frozenset):
        for x in sorted(v, key=sort_key):
            yield term(x), x
    elif isinstance(v, FrozenDict):
        for k, x in v.items():
            yield term(x), k
    else:
        raise BuiltinError(f"{fn}: operand {operand} must be "
                           "string/array/set/object")


def _bi_cidr_contains_matches(cidrs, xs):
    fn = "net.cidr_contains_matches"
    out = set()
    for cidr, i1 in _cidr_match_iter(1, cidrs, fn):
        for x, i2 in _cidr_match_iter(2, xs, fn):
            if _cidr_contains_pair(cidr, x, fn):
                out.add((i1, i2))
    return frozenset(out)


def _bi_regex_template_match(template, value, start, end):
    """Gorilla-mux template matching (topdown/regex_template.go):
    text outside single-char delimiters is literal, inside is regex;
    the assembled pattern is anchored both ends."""
    fn = "regex.template_match"
    tpl = _need_str(template, fn)
    v = _need_str(value, fn)
    ds = _need_str(start, fn)
    de = _need_str(end, fn)
    if len(ds) != 1 or len(de) != 1:
        raise BuiltinError(f"{fn}: delimiters must be exactly one "
                           "character")
    level, idx = 0, 0
    idxs = []
    for i, ch in enumerate(tpl):
        if ch == ds:
            level += 1
            if level == 1:
                idx = i
        elif ch == de:
            level -= 1
            if level == 0:
                idxs.append((idx, i + 1))
            elif level < 0:
                raise BuiltinError(f"{fn}: unbalanced braces in {tpl!r}")
    if level != 0:
        raise BuiltinError(f"{fn}: unbalanced braces in {tpl!r}")
    pattern = ["^"]
    endpos = 0
    for (a, b) in idxs:
        pattern.append(re.escape(tpl[endpos:a]))
        pattern.append("(" + tpl[a + 1: b - 1] + ")")
        endpos = b
    pattern.append(re.escape(tpl[endpos:]))
    pattern.append("$")
    try:
        return bool(compiled_regex("".join(pattern)).search(v))
    except re.error as e:
        raise BuiltinError(f"{fn}: {e}") from None


def _bi_regex_find_all_string_submatch_n(pattern, s, n):
    fn = "regex.find_all_string_submatch_n"
    pat = _need_str(pattern, fn)
    v = _need_str(s, fn)
    limit = int(_need_num(n, fn))
    try:
        rx = compiled_regex(pat)
    except re.error as e:
        raise BuiltinError(f"{fn}: {e}") from None
    out = []
    for m in rx.finditer(v):
        if 0 <= limit <= len(out):
            break
        out.append((m.group(0),)
                   + tuple(g if g is not None else "" for g in m.groups()))
    return tuple(out)


# ---- glob-intersection (regex.globs_match, yashtewari/gintersect port)

def _glob_tokens(s: str, fn: str) -> list:
    """Parse the glob-regex subset (literals, '.', char classes, and
    * / + / ? quantifiers) into (ranges, quantifier) tokens, where
    ranges is a sorted tuple of (lo, hi) codepoint spans."""
    toks = []
    i, n = 0, len(s)
    FULL = ((0, 0x10FFFF),)
    while i < n:
        ch = s[i]
        if ch == ".":
            ranges = FULL
            i += 1
        elif ch == "[":
            j = i + 1
            neg = j < n and s[j] == "^"
            if neg:
                j += 1
            spans = []
            while j < n and s[j] != "]":
                if j + 2 < n and s[j + 1] == "-" and s[j + 2] != "]":
                    spans.append((ord(s[j]), ord(s[j + 2])))
                    j += 3
                else:
                    if s[j] == "\\" and j + 1 < n:
                        j += 1
                    spans.append((ord(s[j]), ord(s[j])))
                    j += 1
            if j >= n:
                raise BuiltinError(f"{fn}: unterminated class in {s!r}")
            spans.sort()
            if neg:
                inv, lo = [], 0
                for a, b in spans:
                    if a > lo:
                        inv.append((lo, a - 1))
                    lo = max(lo, b + 1)
                if lo <= 0x10FFFF:
                    inv.append((lo, 0x10FFFF))
                spans = inv
            ranges = tuple(spans)
            i = j + 1
        elif ch == "\\" and i + 1 < n:
            ranges = ((ord(s[i + 1]), ord(s[i + 1])),)
            i += 2
        elif ch in "*+?":
            raise BuiltinError(f"{fn}: dangling quantifier in {s!r}")
        else:
            ranges = ((ord(ch), ord(ch)),)
            i += 1
        quant = ""
        if i < n and s[i] in "*+?":
            quant = s[i]
            i += 1
        toks.append((ranges, quant))
    return toks


def _glob_nfa(toks):
    """Thompson construction: returns (transitions, accept_state) where
    transitions[state] = [(ranges, next_state)], plus epsilon moves
    encoded via state skipping: state i sits before token i."""
    # state i = position before token i; accept = len(toks)
    eps = {i: set() for i in range(len(toks) + 1)}
    for i, (_r, q) in enumerate(toks):
        if q in ("*", "?"):
            eps[i].add(i + 1)  # skip
    trans = {}
    for i, (r, q) in enumerate(toks):
        # consuming r moves past the token; * and + allow staying
        dests = {i + 1}
        if q in ("*", "+"):
            dests.add(i)
        trans[i] = [(r, d) for d in sorted(dests)]
    return eps, trans, len(toks)


def _eps_close(states, eps):
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in eps.get(s, ()):
            if t not in out:
                out.add(t)
                stack.append(t)
    return out


def _ranges_intersect(a, b) -> bool:
    for lo1, hi1 in a:
        for lo2, hi2 in b:
            if lo1 <= hi2 and lo2 <= hi1:
                return True
    return False


def _bi_regex_globs_match(a, b):
    """True iff the two glob-style regexes can match a COMMON string
    (OPA regex.globs_match via yashtewari/glob-intersection): product
    NFA reachability over intersectable character ranges."""
    fn = "regex.globs_match"
    ta = _glob_tokens(_need_str(a, fn), fn)
    tb = _glob_tokens(_need_str(b, fn), fn)
    eps_a, trans_a, acc_a = _glob_nfa(ta)
    eps_b, trans_b, acc_b = _glob_nfa(tb)
    start = (frozenset(_eps_close({0}, eps_a)),
             frozenset(_eps_close({0}, eps_b)))
    seen = {start}
    stack = [start]
    while stack:
        sa, sb = stack.pop()
        if acc_a in sa and acc_b in sb:
            return True
        # all (range_a, range_b) co-steps with non-empty intersection
        moves_a = [(r, d) for s in sa for (r, d) in trans_a.get(s, ())]
        moves_b = [(r, d) for s in sb for (r, d) in trans_b.get(s, ())]
        for ra, da in moves_a:
            na = frozenset(_eps_close({da}, eps_a))
            for rb, db in moves_b:
                if not _ranges_intersect(ra, rb):
                    continue
                nxt = (na, frozenset(_eps_close({db}, eps_b)))
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
    return False


def _bi_glob_quote_meta(s):
    """Escape glob metacharacters (Go gobwas/glob QuoteMeta)."""
    out = []
    for ch in _need_str(s, "glob.quote_meta"):
        if ch in r"*?\[]{},!":
            out.append("\\")
        out.append(ch)
    return "".join(out)


# ------------------------------------------------- x509 / JWT (crypto)

def _load_certs(s: str, fn: str) -> list:
    """PEM chain or base64-DER (OPA crypto.x509.parse_certificates
    accepts both; topdown/crypto.go)."""
    from cryptography import x509 as _x509

    certs = []
    if "-----BEGIN" in s:
        blocks = re.findall(
            r"-----BEGIN CERTIFICATE-----.*?-----END CERTIFICATE-----",
            s, re.S)
        if not blocks:
            raise BuiltinError(f"{fn}: no PEM certificates found")
        for b in blocks:
            try:
                certs.append(_x509.load_pem_x509_certificate(b.encode()))
            except ValueError as e:
                raise BuiltinError(f"{fn}: {e}") from None
    else:
        try:
            der = _base64.b64decode(s)
        except (_binascii.Error, ValueError) as e:
            raise BuiltinError(f"{fn}: {e}") from None
        from cryptography.hazmat.primitives.serialization import Encoding

        # base64 input may hold one DER cert or a concatenated chain
        while der:
            try:
                cert = _x509.load_der_x509_certificate(der)
            except ValueError as e:
                raise BuiltinError(f"{fn}: {e}") from None
            certs.append(cert)
            der = der[len(cert.public_bytes(Encoding.DER)):]
    return certs


def _name_dict(name) -> "FrozenDict":
    from cryptography.x509.oid import NameOID

    fields = {
        NameOID.COMMON_NAME: "CommonName",
        NameOID.ORGANIZATION_NAME: "Organization",
        NameOID.ORGANIZATIONAL_UNIT_NAME: "OrganizationalUnit",
        NameOID.COUNTRY_NAME: "Country",
        NameOID.LOCALITY_NAME: "Locality",
        NameOID.STATE_OR_PROVINCE_NAME: "Province",
    }
    out: dict = {}
    for attr in name:
        key = fields.get(attr.oid)
        if key == "CommonName":
            out[key] = attr.value
        elif key is not None:
            out.setdefault(key, []).append(attr.value)
    return freeze(out)


def _bi_x509_parse_certificates(s):
    """Array of certificate objects with the Go x509.Certificate JSON
    field names the library surface uses (Subject/Issuer/NotBefore/
    NotAfter/DNSNames/IsCA/SerialNumber/Version); not the full Go
    struct marshal."""
    fn = "crypto.x509.parse_certificates"
    from cryptography import x509 as _x509

    out = []
    for cert in _load_certs(_need_str(s, fn), fn):
        dns_names: list = []
        is_ca = False
        try:
            san = cert.extensions.get_extension_for_class(
                _x509.SubjectAlternativeName)
            dns_names = san.value.get_values_for_type(_x509.DNSName)
        except _x509.ExtensionNotFound:
            pass
        try:
            bc = cert.extensions.get_extension_for_class(
                _x509.BasicConstraints)
            is_ca = bool(bc.value.ca)
        except _x509.ExtensionNotFound:
            pass
        out.append(freeze({
            "Version": cert.version.value + 1,
            "SerialNumber": str(cert.serial_number),
            "Subject": thaw(_name_dict(cert.subject)),
            "Issuer": thaw(_name_dict(cert.issuer)),
            "NotBefore": cert.not_valid_before_utc.strftime(
                "%Y-%m-%dT%H:%M:%SZ"),
            "NotAfter": cert.not_valid_after_utc.strftime(
                "%Y-%m-%dT%H:%M:%SZ"),
            "DNSNames": dns_names,
            "IsCA": is_ca,
        }))
    return tuple(out)


def _jwt_pubkey(cert_or_key: str, fn: str):
    """PEM certificate or PEM public key -> public key object."""
    from cryptography import x509 as _x509
    from cryptography.hazmat.primitives import serialization

    data = cert_or_key.encode()
    if "CERTIFICATE" in cert_or_key:
        try:
            return _x509.load_pem_x509_certificate(data).public_key()
        except ValueError as e:
            raise BuiltinError(f"{fn}: {e}") from None
    try:
        return serialization.load_pem_public_key(data)
    except ValueError as e:
        raise BuiltinError(f"{fn}: {e}") from None


# JOSE raw ECDSA signature widths: 2 coordinates of the curve byte size
# (P-256 -> 32, P-384 -> 48, P-521 -> 66)
_ES_SIG_LEN = {"ES256": 64, "ES384": 96, "ES512": 132}


def _jwt_verify_asym(token, cert, algo: str) -> bool:
    fn = f"io.jwt.verify_{algo.lower()}"
    parts = _need_str(token, fn).split(".")
    if len(parts) != 3:
        return False
    key = _jwt_pubkey(_need_str(cert, fn), fn)
    signed = f"{parts[0]}.{parts[1]}".encode()
    sig = _b64url_decode_pad(parts[2], fn)
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import (
        ec, padding, utils as asym_utils)

    family, bits = algo[:2], algo[2:]
    if family not in ("RS", "PS", "ES") or bits not in ("256", "384", "512"):
        raise BuiltinError(f"{fn}: unsupported algorithm")
    digest = {"256": hashes.SHA256, "384": hashes.SHA384,
              "512": hashes.SHA512}[bits]()
    try:
        if family == "RS":
            key.verify(sig, signed, padding.PKCS1v15(), digest)
        elif family == "PS":
            key.verify(sig, signed,
                       padding.PSS(mgf=padding.MGF1(digest),
                                   salt_length=digest.digest_size),
                       digest)
        else:
            # JOSE: raw r||s (two fixed-width big-endian ints) -> DER
            if len(sig) != _ES_SIG_LEN[algo]:
                return False
            half = len(sig) // 2
            r = int.from_bytes(sig[:half], "big")
            s_ = int.from_bytes(sig[half:], "big")
            der = asym_utils.encode_dss_signature(r, s_)
            key.verify(der, signed, ec.ECDSA(digest))
        return True
    except InvalidSignature:
        return False
    except BuiltinError:
        raise
    except Exception:
        return False


def _bi_jwt_decode_verify(token, constraints):
    """[valid, header, payload] with signature + claim checks
    (topdown/tokens.go builtinJWTDecodeVerify: cert or secret, alg pin,
    iss/aud, exp/nbf against `time` or now)."""
    fn = "io.jwt.decode_verify"
    _need(constraints, "object", fn)
    # exactly one key constraint (topdown/tokens.go parseTokenConstraints:
    # zero keys cannot verify anything, both is ambiguous) — an ERROR,
    # not a false verdict, so policies fail loudly on misconfiguration
    n_keys = ("cert" in constraints) + ("secret" in constraints)
    if n_keys == 0:
        raise BuiltinError(f"{fn}: no key constraint: one of "
                           "'cert' or 'secret' is required")
    if n_keys > 1:
        raise BuiltinError(f"{fn}: duplicate key constraints: 'cert' and "
                           "'secret' are mutually exclusive")
    try:
        header, payload, _sig = _bi_jwt_decode(token)
    except BuiltinError:
        return (False, FrozenDict(), FrozenDict())
    alg = header.get("alg")
    want_alg = constraints.get("alg")
    if want_alg is not None and alg != want_alg:
        return (False, FrozenDict(), FrozenDict())
    ok = False
    if alg in _HS_DIGESTS and "secret" in constraints:
        ok = _jwt_verify_hs(token, constraints["secret"], alg)
    elif alg in ("RS256", "PS256", "ES256", "RS384", "PS384", "ES384",
                 "RS512", "PS512", "ES512") and "cert" in constraints:
        ok = _jwt_verify_asym(token, constraints["cert"], alg)
    if not ok:
        return (False, FrozenDict(), FrozenDict())
    now_ns = constraints.get("time", int(_time.time() * 1e9))
    now_s = _need_num(now_ns, fn) / 1e9
    exp = payload.get("exp")
    if exp is not None and now_s >= _need_num(exp, fn):
        return (False, FrozenDict(), FrozenDict())
    nbf = payload.get("nbf")
    if nbf is not None and now_s < _need_num(nbf, fn):
        return (False, FrozenDict(), FrozenDict())
    iss = constraints.get("iss")
    if iss is not None and payload.get("iss") != iss:
        return (False, FrozenDict(), FrozenDict())
    aud = constraints.get("aud")
    if aud is not None:
        have = payload.get("aud")
        have_set = set(have) if isinstance(have, tuple) else {have}
        if aud not in have_set:
            return (False, FrozenDict(), FrozenDict())
    elif payload.get("aud") is not None:
        # token carries an audience the caller did not constrain: reject
        # (topdown/tokens.go validAudience)
        return (False, FrozenDict(), FrozenDict())
    return (True, header, payload)


def _b64url_nopad(b: bytes) -> str:
    return _base64.urlsafe_b64encode(b).decode().rstrip("=")


def _jwk_sign(alg: str, key, signed: bytes, fn: str) -> bytes:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import (
        ec, padding, rsa, utils as asym_utils)

    _need(key, "object", fn)
    kty = key.get("kty")
    if alg in ("HS256", "HS384", "HS512"):
        if kty != "oct":
            raise BuiltinError(f"{fn}: {alg} needs an oct key")
        secret = _b64url_decode_pad(_need_str(key.get("k"), fn), fn)
        digest = {"HS256": _hashlib.sha256, "HS384": _hashlib.sha384,
                  "HS512": _hashlib.sha512}[alg]
        return _hmac_mod.new(secret, signed, digest).digest()

    def _i(name):
        v = key.get(name)
        if v is None:
            raise BuiltinError(f"{fn}: JWK missing {name!r}")
        return int.from_bytes(_b64url_decode_pad(_need_str(v, fn), fn),
                              "big")

    if alg == "RS256":
        if kty != "RSA":
            raise BuiltinError(f"{fn}: RS256 needs an RSA key")
        pub = rsa.RSAPublicNumbers(_i("e"), _i("n"))
        priv = rsa.RSAPrivateNumbers(
            p=_i("p"), q=_i("q"), d=_i("d"), dmp1=_i("dp"), dmq1=_i("dq"),
            iqmp=_i("qi"), public_numbers=pub).private_key()
        return priv.sign(signed, padding.PKCS1v15(), hashes.SHA256())
    if alg == "ES256":
        if kty != "EC":
            raise BuiltinError(f"{fn}: ES256 needs an EC key")
        priv = ec.derive_private_key(_i("d"), ec.SECP256R1())
        der = priv.sign(signed, ec.ECDSA(hashes.SHA256()))
        r, s_ = asym_utils.decode_dss_signature(der)
        return r.to_bytes(32, "big") + s_.to_bytes(32, "big")
    raise BuiltinError(f"{fn}: unsupported algorithm {alg!r}")


def _bi_jwt_encode_sign(headers, payload, key):
    """Signed JWS from object headers/payload + JWK (topdown/tokens.go
    builtinJWTEncodeSign; HS*/RS256/ES256)."""
    fn = "io.jwt.encode_sign"
    _need(headers, "object", fn)
    _need(payload, "object", fn)
    alg = headers.get("alg")
    if not isinstance(alg, str):
        raise BuiltinError(f"{fn}: headers must carry a string alg")
    h = _b64url_nopad(_canon_json(headers).encode())
    p = _b64url_nopad(_canon_json(payload).encode())
    signed = f"{h}.{p}".encode()
    sig = _jwk_sign(alg, key, signed, fn)
    return f"{h}.{p}.{_b64url_nopad(sig)}"


def _bi_jwt_encode_sign_raw(headers, payload, key):
    """Like encode_sign but headers/payload/key arrive as JSON strings
    (topdown/tokens.go builtinJWTEncodeSignRaw)."""
    fn = "io.jwt.encode_sign_raw"
    try:
        hdr = freeze(json.loads(_need_str(headers, fn)))
        key_obj = freeze(json.loads(_need_str(key, fn)))
        json.loads(_need_str(payload, fn))  # must be valid JSON
    except ValueError as e:
        raise BuiltinError(f"{fn}: {e}") from None
    _need(hdr, "object", fn)
    alg = hdr.get("alg")
    if not isinstance(alg, str):
        raise BuiltinError(f"{fn}: headers must carry a string alg")
    h = _b64url_nopad(_need_str(headers, fn).encode())
    p = _b64url_nopad(_need_str(payload, fn).encode())
    signed = f"{h}.{p}".encode()
    sig = _jwk_sign(alg, key_obj, signed, fn)
    return f"{h}.{p}.{_b64url_nopad(sig)}"


# ----------------------------------------------------- gated http.send

def _bi_http_send(req):
    """Outbound HTTP from policy (topdown/http.go). DISABLED unless
    GATEKEEPER_TPU_ENABLE_HTTP_SEND=1: admission policies phoning out
    add unbounded tail latency and an exfiltration channel, so the gate
    is explicit and the error says exactly how to open it."""
    import os as _os

    fn = "http.send"
    _need(req, "object", fn)
    if _os.environ.get("GATEKEEPER_TPU_ENABLE_HTTP_SEND") != "1":
        raise BuiltinError(
            f"{fn}: disabled (set GATEKEEPER_TPU_ENABLE_HTTP_SEND=1 to "
            "allow outbound HTTP from policies)")
    import urllib.error
    import urllib.request

    method = _need_str(req.get("method", "GET"), fn).upper()
    url = _need_str(req.get("url", ""), fn)
    if not url.startswith(("http://", "https://")):
        raise BuiltinError(f"{fn}: unsupported url {url!r}")
    body = None
    if "body" in req:
        body = _canon_json(req["body"]).encode()
    elif "raw_body" in req:
        body = _need_str(req["raw_body"], fn).encode()
    headers = {str(k): str(v)
               for k, v in (req.get("headers") or FrozenDict()).items()}
    timeout = _need_num(req.get("timeout", 5), fn)
    r = urllib.request.Request(url, data=body, headers=headers,
                               method=method)
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            raw = resp.read().decode("utf-8", "replace")
            status = resp.status
            resp_headers = {k.lower(): v for k, v in resp.headers.items()}
    except urllib.error.HTTPError as e:
        raw = e.read().decode("utf-8", "replace")
        status = e.code
        resp_headers = {k.lower(): v for k, v in e.headers.items()}
    except (urllib.error.URLError, OSError) as e:
        if req.get("raise_error", True):
            raise BuiltinError(f"{fn}: {e}") from None
        return freeze({"status_code": 0, "error": str(e)})
    out = {"status_code": status, "raw_body": raw,
           "headers": resp_headers}
    try:
        out["body"] = json.loads(raw)
    except ValueError:
        out["body"] = None
    return freeze(out)


# ------------------------------------------------------- small parity

def _bi_opa_runtime():
    """Deployment environment view (topdown/runtime.go): env + version.
    Commonly used to read env-injected configuration in policies."""
    import os as _os

    return freeze({"env": dict(_os.environ),
                   "version": "gatekeeper-tpu"})


def _bi_rego_parse_module(filename, src):
    """Parse rego source and return an AST summary (package path + rule
    names/kinds). OPA returns its own Go AST JSON marshal; this is the
    native AST's summary — documented divergence, same use cases
    (introspecting a module's shape from policy)."""
    fn = "rego.parse_module"
    from .parser import ParseError, parse_module as _parse

    try:
        mod = _parse(_need_str(src, fn), _need_str(filename, fn))
    except ParseError as e:
        raise BuiltinError(f"{fn}: {e}") from None
    return freeze({
        "package": {"path": ["data"] + list(mod.package)},
        "rules": [{"name": r.name, "kind": r.kind,
                   "default": bool(getattr(r, "is_default", False))}
                  for r in mod.rules],
    })


def _bi_minus(a, b):
    # '-' doubles as set difference (named form of the infix operator)
    if isinstance(a, frozenset) and isinstance(b, frozenset):
        return a - b
    return _need_num(a, "minus") - _need_num(b, "minus")


def _bi_div(a, b):
    d = _need_num(b, "div")
    if d == 0:
        raise BuiltinError("div: divide by zero")
    out = _need_num(a, "div") / d
    return int(out) if float(out).is_integer() else out


def _bi_rem(a, b):
    x, y = _need_num(a, "rem"), _need_num(b, "rem")
    if y == 0:
        raise BuiltinError("rem: modulo by zero")
    if not (float(x).is_integer() and float(y).is_integer()):
        raise BuiltinError("rem: modulo on floating-point number")
    return int(_math.fmod(int(x), int(y)))


def _bi_set_diff(a, b):
    _need(a, "set", "set_diff")
    _need(b, "set", "set_diff")
    return a - b


def _bi_set_and(a, b):
    _need(a, "set", "and")
    _need(b, "set", "and")
    return a & b


def _bi_set_or(a, b):
    _need(a, "set", "or")
    _need(b, "set", "or")
    return a | b


BUILTINS.update({
    ("time", "parse_ns"): _bi_time_parse_ns,
    ("time", "parse_duration_ns"): _bi_time_parse_duration_ns,
    ("time", "format"): _bi_time_format,
    ("net", "cidr_expand"): _bi_cidr_expand,
    ("net", "cidr_merge"): _bi_cidr_merge,
    ("net", "cidr_contains_matches"): _bi_cidr_contains_matches,
    ("net", "cidr_overlap"): lambda c, x: _cidr_contains_pair(
        c, x, "net.cidr_overlap"),  # deprecated alias of cidr_contains
    ("regex", "template_match"): _bi_regex_template_match,
    ("regex", "globs_match"): _bi_regex_globs_match,
    ("regex", "find_all_string_submatch_n"):
        _bi_regex_find_all_string_submatch_n,
    ("glob", "quote_meta"): _bi_glob_quote_meta,
    ("crypto", "x509", "parse_certificates"): _bi_x509_parse_certificates,
    **{("io", "jwt", f"verify_{fam}{bits}"):
       (lambda t, c, _a=f"{fam.upper()}{bits}": _jwt_verify_asym(t, c, _a))
       for fam in ("rs", "ps", "es") for bits in ("256", "384", "512")},
    **{("io", "jwt", f"verify_hs{bits}"):
       (lambda t, c, _a=f"HS{bits}": _jwt_verify_hs(t, c, _a))
       for bits in ("384", "512")},
    ("io", "jwt", "decode_verify"): _bi_jwt_decode_verify,
    ("io", "jwt", "encode_sign"): _bi_jwt_encode_sign,
    ("io", "jwt", "encode_sign_raw"): _bi_jwt_encode_sign_raw,
    ("http", "send"): _bi_http_send,
    ("opa", "runtime"): _bi_opa_runtime,
    ("rego", "parse_module"): _bi_rego_parse_module,
    ("set_diff",): _bi_set_diff,
    ("cast_null",): lambda v: _need(v, "null", "cast_null"),
    ("cast_object",): lambda v: _need(v, "object", "cast_object"),
    ("cast_set",): lambda v: _need(v, "set", "cast_set"),
    # named forms of the infix operators (callable in OPA)
    ("plus",): lambda a, b: _need_num(a, "plus") + _need_num(b, "plus"),
    ("minus",): _bi_minus,
    ("mul",): lambda a, b: _need_num(a, "mul") * _need_num(b, "mul"),
    ("div",): _bi_div,
    ("rem",): _bi_rem,
    ("eq",): rego_eq,
    ("gt",): lambda a, b: sort_key(a) > sort_key(b),
    ("gte",): lambda a, b: sort_key(a) >= sort_key(b),
    ("lt",): lambda a, b: sort_key(a) < sort_key(b),
    ("lte",): lambda a, b: sort_key(a) <= sort_key(b),
    ("and",): _bi_set_and,
    ("or",): _bi_set_or,
})

# decode_verify consults the wall clock when no "time" constraint is
# given: memoizing it would freeze token validity across requests (an
# expired JWT would keep admitting workloads)
NONDETERMINISTIC.update({("http", "send"), ("opa", "runtime"),
                         ("io", "jwt", "decode_verify")})
