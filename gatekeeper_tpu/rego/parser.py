"""Recursive-descent parser for the Rego subset (see ast.py for coverage).

Newline discipline: rule and comprehension bodies separate literals with
NEWLINE or `;`; inside any bracketed term context newlines are skipped. This
matches how the reference corpus formats multi-line calls, e.g. the
match_expression_violated(...) call spanning four lines in
pkg/target/regolib/src.rego.
"""

from __future__ import annotations

from .ast import (
    ArrayCompr,
    ArrayLit,
    Assign,
    BinOp,
    Call,
    Literal,
    Module,
    ObjectCompr,
    ObjectLit,
    Ref,
    Rule,
    Scalar,
    SetCompr,
    SetLit,
    SomeDecl,
    Unify,
    UnaryMinus,
    Var,
    WithMod,
)
from .scanner import Token, scan


class ParseError(SyntaxError):
    pass


_KEYWORDS = {"package", "import", "as", "not", "with", "some", "default", "else",
             "true", "false", "null"}

_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}
_ADD_OPS = {"+", "-", "|", "&"}
_MUL_OPS = {"*", "/", "%"}


class Parser:
    def __init__(self, src: str, name: str = "<rego>"):
        self.toks: list[Token] = scan(src, name)
        self.pos = 0
        self.name = name
        self._wc = 0

    # ------------------------------------------------------------ plumbing

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.pos + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def at(self, kind: str, value=None) -> bool:
        t = self.peek()
        return t.kind == kind and (value is None or t.value == value)

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value in ops

    def expect(self, kind: str, value=None) -> Token:
        t = self.peek()
        if t.kind != kind or (value is not None and t.value != value):
            raise ParseError(
                f"{self.name}:{t.line}: expected {value or kind}, got {t.kind}({t.value!r})"
            )
        return self.next()

    def skip_nl(self):
        while self.at("NEWLINE"):
            self.next()

    def err(self, msg: str):
        t = self.peek()
        raise ParseError(f"{self.name}:{t.line}: {msg} (at {t.kind}({t.value!r}))")

    # ------------------------------------------------------------ module

    def parse_module(self) -> Module:
        self.skip_nl()
        self.expect("IDENT", "package")
        package = tuple(self._parse_dotted_name())
        imports = []
        rules = []
        self.skip_nl()
        while self.at("IDENT", "import"):
            self.next()
            path = tuple(self._parse_dotted_name())
            alias = None
            if self.at("IDENT", "as"):
                self.next()
                alias = self.expect("IDENT").value
            imports.append((path, alias))
            self.skip_nl()
        while not self.at("EOF"):
            rules.append(self._parse_rule())
            self.skip_nl()
        return Module(package=package, imports=tuple(imports), rules=tuple(rules),
                      source_name=self.name)

    def _parse_dotted_name(self) -> list[str]:
        parts = [self.expect("IDENT").value]
        while self.at_op("."):
            self.next()
            t = self.peek()
            if t.kind in ("IDENT", "STRING"):
                parts.append(self.next().value)
            else:
                self.err("expected name segment")
        return parts

    # ------------------------------------------------------------ rules

    def _parse_rule(self) -> Rule:
        line = self.peek().line
        is_default = False
        if self.at("IDENT", "default"):
            self.next()
            is_default = True
        name_tok = self.expect("IDENT")
        name = name_tok.value
        if name in _KEYWORDS:
            self.err(f"keyword {name!r} cannot start a rule")

        if self.at_op("(") and not is_default:
            self.next()
            args = self._parse_term_list(")")
            value = None
            if self.at_op("=", ":="):
                self.next()
                value = self._parse_relation()
            body = self._parse_opt_body()
            return Rule(name=name, kind="function", args=tuple(args),
                        value=value or Scalar(True), body=body, line=line)

        if self.at_op("[") and not is_default:
            self.next()
            self.skip_nl()
            key = self._parse_relation()
            self.skip_nl()
            self.expect("OP", "]")
            if self.at_op("=", ":="):
                self.next()
                value = self._parse_relation()
                body = self._parse_opt_body()
                return Rule(name=name, kind="partial_object", key=key, value=value,
                            body=body, line=line)
            body = self._parse_opt_body()
            return Rule(name=name, kind="partial_set", key=key, body=body, line=line)

        value = None
        if self.at_op("=", ":="):
            self.next()
            value = self._parse_relation()
        body = () if is_default else self._parse_opt_body()
        return Rule(name=name, kind="complete", value=value or Scalar(True),
                    body=body, is_default=is_default, line=line)

    def _parse_opt_body(self) -> tuple:
        if self.at_op("{"):
            self.next()
            return self._parse_body("}")
        return ()

    def _parse_body(self, end_op: str) -> tuple:
        """Literals separated by NEWLINE/';' until the closing op (consumed)."""
        lits = []
        while True:
            while self.at("NEWLINE") or self.at_op(";"):
                self.next()
            if self.at_op(end_op):
                self.next()
                break
            if self.at("EOF"):
                self.err(f"unterminated body, expected {end_op}")
            lits.append(self._parse_literal())
            if not (self.at("NEWLINE") or self.at_op(";") or self.at_op(end_op)):
                self.err("expected end of expression")
        return tuple(lits)

    # ------------------------------------------------------------ literals

    def _parse_literal(self) -> Literal:
        line = self.peek().line
        if self.at("IDENT", "some"):
            self.next()
            names = [self.expect("IDENT").value]
            while self.at_op(","):
                self.next()
                names.append(self.expect("IDENT").value)
            return Literal(expr=SomeDecl(tuple(names)), line=line)
        negated = False
        if self.at("IDENT", "not"):
            self.next()
            negated = True
        expr = self._parse_expr()
        withs = []
        # `with` modifiers may start on a continuation line, and the term
        # after `as` may too (seen throughout the reference's src_test.rego
        # files) — look ahead through newlines for the `with` keyword
        while self.at("IDENT", "with") or self._nl_then_with():
            self.skip_nl()
            self.next()
            target = tuple(self._parse_with_target())
            self.expect("IDENT", "as")
            self.skip_nl()
            value = self._parse_relation()
            withs.append(WithMod(target=target, value=value))
        return Literal(expr=expr, negated=negated, withs=tuple(withs), line=line)

    def _nl_then_with(self) -> bool:
        k = 0
        while self.peek(k).kind == "NEWLINE":
            k += 1
        t = self.peek(k)
        return k > 0 and t.kind == "IDENT" and t.value == "with"

    def _parse_with_target(self) -> list:
        parts = [self.expect("IDENT").value]
        while True:
            if self.at_op("."):
                self.next()
                parts.append(self.expect("IDENT").value)
            elif self.at_op("["):
                self.next()
                parts.append(self.expect("STRING").value)
                self.expect("OP", "]")
            else:
                return parts

    def _parse_expr(self):
        lhs = self._parse_relation()
        if self.at_op(":="):
            self.next()
            return Assign(lhs=lhs, rhs=self._parse_relation())
        if self.at_op("="):
            self.next()
            return Unify(lhs=lhs, rhs=self._parse_relation())
        return lhs

    # ------------------------------------------------------------ terms

    def _parse_relation(self, stop_union: bool = False):
        lhs = self._parse_addsub(stop_union)
        if self.at_op(*_CMP_OPS):
            op = self.next().value
            rhs = self._parse_addsub(stop_union)
            return BinOp(op=op, lhs=lhs, rhs=rhs)
        return lhs

    def _parse_addsub(self, stop_union: bool = False):
        lhs = self._parse_muldiv()
        while self.at_op(*_ADD_OPS):
            if stop_union and self.at_op("|"):
                break
            op = self.next().value
            self.skip_nl()
            lhs = BinOp(op=op, lhs=lhs, rhs=self._parse_muldiv())
        return lhs

    def _parse_muldiv(self):
        lhs = self._parse_unary()
        while self.at_op(*_MUL_OPS):
            op = self.next().value
            self.skip_nl()
            lhs = BinOp(op=op, lhs=lhs, rhs=self._parse_unary())
        return lhs

    def _parse_unary(self):
        if self.at_op("-"):
            self.next()
            t = self._parse_unary()
            if isinstance(t, Scalar) and isinstance(t.value, (int, float)):
                return Scalar(-t.value)
            return UnaryMinus(t)
        return self._parse_postfix()

    def _parse_postfix(self):
        term = self._parse_primary()
        while True:
            if self.at_op("."):
                self.next()
                seg = self.expect("IDENT").value
                if self.at_op("("):
                    # dotted builtin call like glob.match(...)
                    fn = self._ref_to_name(term)
                    fn.append(seg)
                    self.next()
                    args = self._parse_term_list(")")
                    term = Call(fn=tuple(fn), args=tuple(args))
                    continue
                term = self._ref_append(term, Scalar(seg))
                continue
            if self.at_op("["):
                self.next()
                self.skip_nl()
                idx = self._parse_relation()
                self.skip_nl()
                self.expect("OP", "]")
                term = self._ref_append(term, idx)
                continue
            if self.at_op("("):
                fn = self._ref_to_name(term)
                self.next()
                args = self._parse_term_list(")")
                term = Call(fn=tuple(fn), args=tuple(args))
                continue
            return term

    def _ref_append(self, term, arg):
        if isinstance(term, Ref):
            return Ref(base=term.base, args=term.args + (arg,))
        return Ref(base=term, args=(arg,))

    def _ref_to_name(self, term) -> list:
        if isinstance(term, Var):
            return [term.name]
        if isinstance(term, Ref) and isinstance(term.base, Var):
            parts = [term.base.name]
            for a in term.args:
                if isinstance(a, Scalar) and isinstance(a.value, str):
                    parts.append(a.value)
                else:
                    self.err("function name must be a static dotted path")
            return parts
        self.err("cannot call a non-name term")

    def _parse_term_list(self, end_op: str) -> list:
        self.skip_nl()
        items = []
        if self.at_op(end_op):
            self.next()
            return items
        while True:
            items.append(self._parse_relation())
            self.skip_nl()
            if self.at_op(","):
                self.next()
                self.skip_nl()
                continue
            self.expect("OP", end_op)
            return items

    def _parse_primary(self):
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return Scalar(t.value)
        if t.kind == "STRING":
            self.next()
            return Scalar(t.value)
        if t.kind == "IDENT":
            if t.value == "true":
                self.next()
                return Scalar(True)
            if t.value == "false":
                self.next()
                return Scalar(False)
            if t.value == "null":
                self.next()
                return Scalar(None)
            if t.value == "_":
                self.next()
                self._wc += 1
                return Var(f"$wc{self._wc}")
            if t.value == "not" or t.value == "some" or t.value == "with":
                self.err(f"unexpected keyword {t.value!r} in term")
            self.next()
            return Var(t.value)
        if t.kind == "OP" and t.value == "(":
            self.next()
            self.skip_nl()
            inner = self._parse_expr()
            self.skip_nl()
            self.expect("OP", ")")
            return inner
        if t.kind == "OP" and t.value == "[":
            self.next()
            self.skip_nl()
            if self.at_op("]"):
                self.next()
                return ArrayLit(())
            head = self._parse_relation(stop_union=True)
            self.skip_nl()
            if self.at_op("|"):
                self.next()
                body = self._parse_body("]")
                return ArrayCompr(head=head, body=body)
            items = [head]
            while self.at_op(","):
                self.next()
                self.skip_nl()
                if self.at_op("]"):
                    break
                items.append(self._parse_relation())
                self.skip_nl()
            self.expect("OP", "]")
            return ArrayLit(tuple(items))
        if t.kind == "OP" and t.value == "{":
            return self._parse_brace_term()
        self.err("expected a term")

    def _parse_brace_term(self):
        self.expect("OP", "{")
        self.skip_nl()
        if self.at_op("}"):
            self.next()
            return ObjectLit(())
        first = self._parse_relation(stop_union=True)
        self.skip_nl()
        if self.at_op(":"):
            self.next()
            self.skip_nl()
            value = self._parse_relation(stop_union=True)
            self.skip_nl()
            if self.at_op("|"):
                self.next()
                body = self._parse_body("}")
                return ObjectCompr(key=first, value=value, body=body)
            items = [(first, value)]
            while self.at_op(","):
                self.next()
                self.skip_nl()
                if self.at_op("}"):
                    break
                k = self._parse_relation()
                self.skip_nl()
                self.expect("OP", ":")
                self.skip_nl()
                v = self._parse_relation()
                items.append((k, v))
                self.skip_nl()
            self.expect("OP", "}")
            return ObjectLit(tuple(items))
        if self.at_op("|"):
            self.next()
            body = self._parse_body("}")
            return SetCompr(head=first, body=body)
        items = [first]
        while self.at_op(","):
            self.next()
            self.skip_nl()
            if self.at_op("}"):
                break
            items.append(self._parse_relation())
            self.skip_nl()
        self.expect("OP", "}")
        return SetLit(tuple(items))


def parse_module(src: str, name: str = "<rego>") -> Module:
    return Parser(src, name).parse_module()
