"""Tree-walking reference interpreter for the Rego subset.

This is the framework's semantic oracle and fallback driver — the analog of
the reference's vendored OPA topdown evaluator
(vendor/github.com/open-policy-agent/opa/topdown, ~12k LoC Go). The
vectorizing TPU compiler (ir/) is validated against it, and templates whose
Rego falls outside the vectorizable subset run here.

Evaluation model: generator-based top-down query evaluation with
backtracking. Bindings live in per-rule-scope dicts and are undone through a
trail (mark/undo), so generators can yield mid-solution. Semantics mirrored
from OPA:

  * undefined vs false tri-state: only `false` and undefined fail a body
    literal; 0, "", [] and {} are truthy.
  * `not e` succeeds when e is undefined or false; bindings never escape.
  * unification literals succeed on successful unification regardless of the
    unified value's truthiness (e.g. `good = startswith(img, repo)` binds
    good=false and succeeds — library/general/allowedrepos/src.rego).
  * builtin errors make expressions undefined (non-strict mode).
  * complete/function rules with multiple clauses must agree on the output
    (conflict error otherwise); partial rules union their outputs.
  * refs with unbound bracket vars enumerate (objects by key, arrays by
    index, sets by member, `data` by tree children including virtual docs).
  * `with input as X` / `with data.p as X` scoped overrides, including
    cache isolation (used by src_test.rego suites and the target matcher's
    matching_reviews_and_constraints).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

from ..utils.values import FrozenDict, freeze, rego_eq, sort_key
from . import ast as A
from .builtins import BUILTINS, BuiltinError
from .safety import reorder_module


class RegoError(Exception):
    """Evaluation error (conflict, unsafe var, recursion limit...)."""


class _Undef:
    __slots__ = ()

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undef()

class _Fresh:
    __slots__ = ()


FRESH = _Fresh()  # marks `some`-declared locals as explicitly unbound

_MISSING = object()
_MAX_DEPTH = 200


class DataNode:
    """Cursor into the data document = base data tree + mounted packages."""

    __slots__ = ("path", "base")

    def __init__(self, path: tuple, base: Any):
        self.path = path
        self.base = base  # plain dict tree / frozen value / _MISSING


class Ctx:
    __slots__ = (
        "interp",
        "input_stack",
        "data_overrides",
        "pkg_stack",
        "trail",
        "cache",
        "frame",
        "next_frame",
        "depth",
    )

    def __init__(self, interp: "Interpreter", input_value: Any):
        self.interp = interp
        self.input_stack = [input_value]
        self.data_overrides: list[dict[tuple, Any]] = [{}]
        self.pkg_stack: list[tuple] = []
        self.trail: list = []
        self.cache: dict = {}
        self.frame = 0
        self.next_frame = 1
        self.depth = 0

    @property
    def input(self):
        return self.input_stack[-1]

    def mark(self) -> int:
        return len(self.trail)

    def bind(self, env: dict, name: str, value: Any):
        old = env.get(name, _MISSING)
        self.trail.append((env, name, old))
        env[name] = value

    def undo(self, mark: int):
        t = self.trail
        while len(t) > mark:
            env, name, old = t.pop()
            if old is _MISSING:
                env.pop(name, None)
            else:
                env[name] = old


def _is_unbound(env: dict, name: str) -> bool:
    v = env.get(name, _MISSING)
    return v is _MISSING or v is FRESH


class Interpreter:
    def __init__(self, modules: Optional[dict[str, A.Module]] = None,
                 data: Optional[dict] = None):
        # modules keyed by an owner id so the Client can replace/remove them
        self.modules: dict[str, A.Module] = {}
        self.data = data if data is not None else {}
        self.packages: dict[tuple, dict[str, list[A.Rule]]] = {}
        self._pkg_prefixes: set[tuple] = set()
        if modules:
            for k, m in modules.items():
                self.modules[k] = reorder_module(m)
            self._reindex()

    # ------------------------------------------------------------ modules

    def put_module(self, name: str, module: A.Module):
        self.modules[name] = reorder_module(module)
        self._reindex()

    def delete_module(self, name: str):
        self.modules.pop(name, None)
        self._reindex()

    def _reindex(self):
        self.packages = {}
        self._pkg_prefixes = set()
        for m in self.modules.values():
            pkg = self.packages.setdefault(m.package, {})
            for r in m.rules:
                pkg.setdefault(r.name, []).append(r)
            for i in range(1, len(m.package) + 1):
                self._pkg_prefixes.add(m.package[:i])

    # ------------------------------------------------------------ data API

    def put_data(self, path: tuple, value: Any):
        """Install a frozen copy of `value` at `path` in base data."""
        node = self.data
        for seg in path[:-1]:
            nxt = node.get(seg)
            if not isinstance(nxt, dict):
                nxt = {}
                node[seg] = nxt
            node = nxt
        node[path[-1]] = freeze(value)

    def delete_data(self, path: tuple) -> bool:
        node = self.data
        for seg in path[:-1]:
            node = node.get(seg)
            if not isinstance(node, dict):
                return False
        return node.pop(path[-1], _MISSING) is not _MISSING

    def get_data(self, path: tuple):
        node: Any = self.data
        for seg in path:
            if isinstance(node, dict):
                node = node.get(seg, _MISSING)
            elif isinstance(node, FrozenDict):
                node = node.get(seg, _MISSING)
            else:
                return UNDEF
            if node is _MISSING:
                return UNDEF
        return node

    # ------------------------------------------------------------ queries

    def eval_rule(self, pkg: tuple, name: str, input_value: Any = None,
                  overrides: Optional[dict] = None):
        """Evaluate a rule to its document. Returns a frozen value or UNDEF.

        `overrides` mounts values into the data document for the duration of
        the query, keyed by path tuple — the driver uses it to bind
        `data.inventory` the way the reference hook does with
        `with data.inventory as inv` (regolib/src.go:30-31)."""
        ctx = Ctx(self, freeze(input_value))
        if overrides:
            ctx.data_overrides[0] = {
                tuple(path): freeze(v) for path, v in overrides.items()
            }
        return self._rule_value(pkg, name, ctx)

    def run_tests(self, pkg: tuple) -> dict[str, bool]:
        """Run all test_* rules of a package (the opa-test analog used for
        conformance against the reference's src_test.rego suites)."""
        out = {}
        rules = self.packages.get(pkg, {})
        for name in rules:
            if name.startswith("test_"):
                ctx = Ctx(self, None)
                v = self._rule_value(pkg, name, ctx)
                out[name] = v is not UNDEF and v is not False
        return out

    # ------------------------------------------------------------ rules

    def _rules(self, pkg: tuple, name: str) -> Optional[list]:
        return self.packages.get(pkg, {}).get(name)

    def _rule_value(self, pkg: tuple, name: str, ctx: Ctx):
        key = (pkg, name, ctx.frame)
        if key in ctx.cache:
            return ctx.cache[key]
        rules = self._rules(pkg, name)
        if not rules:
            return UNDEF
        kind = rules[0].kind
        ctx.depth += 1
        if ctx.depth > _MAX_DEPTH:
            raise RegoError(f"max eval depth exceeded in {'.'.join(pkg)}.{name}")
        ctx.pkg_stack.append(pkg)
        try:
            if kind == "complete":
                result = self._eval_complete(rules, ctx)
            elif kind == "partial_set":
                acc = set()
                for r in rules:
                    env: dict = {}
                    mark = ctx.mark()
                    try:
                        for _ in self._solve(r.body, 0, env, ctx):
                            for kv in self._iter_term(r.key, env, ctx):
                                acc.add(kv)
                    finally:
                        ctx.undo(mark)
                result = frozenset(acc)
            elif kind == "partial_object":
                obj: dict = {}
                for r in rules:
                    env = {}
                    mark = ctx.mark()
                    try:
                        for _ in self._solve(r.body, 0, env, ctx):
                            for kv in self._iter_term(r.key, env, ctx):
                                for vv in self._iter_term(r.value, env, ctx):
                                    if kv in obj and not rego_eq(obj[kv], vv):
                                        raise RegoError(
                                            f"object rule {name}: conflicting values for key {kv!r}"
                                        )
                                    obj[kv] = vv
                    finally:
                        ctx.undo(mark)
                result = FrozenDict(obj)
            else:
                raise RegoError(f"{'.'.join(pkg)}.{name} is a function, not a document")
        finally:
            ctx.pkg_stack.pop()
            ctx.depth -= 1
        ctx.cache[key] = result
        return result

    def _eval_complete(self, rules: list, ctx: Ctx):
        outputs: list = []
        default_val = UNDEF
        for r in rules:
            if r.is_default:
                env: dict = {}
                for v in self._iter_term(r.value, env, ctx):
                    default_val = v
                continue
            env = {}
            mark = ctx.mark()
            try:
                for _ in self._solve(r.body, 0, env, ctx):
                    for v in self._iter_term(r.value, env, ctx):
                        if not any(rego_eq(v, o) for o in outputs):
                            outputs.append(v)
            finally:
                ctx.undo(mark)
        if len(outputs) > 1:
            raise RegoError(
                f"complete rule {rules[0].name}: produced multiple outputs {outputs!r}"
            )
        if outputs:
            return outputs[0]
        return default_val

    def _call_function(self, pkg: tuple, name: str, argvals: tuple, ctx: Ctx):
        rules = self._rules(pkg, name)
        if not rules:
            return UNDEF
        outputs: list = []
        ctx.depth += 1
        if ctx.depth > _MAX_DEPTH:
            raise RegoError(f"max eval depth exceeded calling {name}")
        ctx.pkg_stack.append(pkg)
        try:
            for r in rules:
                if len(r.args) != len(argvals):
                    continue
                env: dict = {}
                mark = ctx.mark()
                try:
                    if not self._unify_pattern_all(r.args, argvals, env, ctx):
                        continue
                    for _ in self._solve(r.body, 0, env, ctx):
                        for v in self._iter_term(r.value, env, ctx):
                            if not any(rego_eq(v, o) for o in outputs):
                                outputs.append(v)
                finally:
                    ctx.undo(mark)
        finally:
            ctx.pkg_stack.pop()
            ctx.depth -= 1
        if len(outputs) > 1:
            raise RegoError(f"function {name}: conflicting outputs {outputs!r}")
        return outputs[0] if outputs else UNDEF

    # ------------------------------------------------------------ body solving

    def _solve(self, lits: tuple, i: int, env: dict, ctx: Ctx) -> Iterator[None]:
        if i == len(lits):
            yield
            return
        for _ in self._solve_literal(lits[i], env, ctx):
            yield from self._solve(lits, i + 1, env, ctx)

    def _solve_literal(self, lit: A.Literal, env: dict, ctx: Ctx) -> Iterator[None]:
        if lit.withs:
            # The override must cover ONLY this literal's evaluation. A lazy
            # `yield from` would leave the override active while subsequent
            # literals run (generator suspended inside the with scope), so
            # solutions are materialized eagerly — state restored — then
            # their bindings replayed.
            saved_frame = ctx.frame
            pushed_input = 0
            pushed_data = 0
            mark = ctx.mark()
            solutions: list[dict] = []
            try:
                for w in lit.withs:
                    vals = list(self._iter_term(w.value, env, ctx))
                    if not vals:
                        return  # override value undefined => literal undefined
                    if w.target == ("input",) or (
                        len(w.target) > 1 and w.target[0] == "input"
                    ):
                        if w.target == ("input",):
                            ctx.input_stack.append(vals[0])
                        else:
                            base = ctx.input
                            ctx.input_stack.append(
                                _set_in(base, w.target[1:], vals[0])
                            )
                        pushed_input += 1
                    elif w.target[0] == "data":
                        ov = dict(ctx.data_overrides[-1])
                        ov[tuple(w.target[1:])] = vals[0]
                        ctx.data_overrides.append(ov)
                        pushed_data += 1
                    else:
                        raise RegoError(f"with target {w.target!r} unsupported")
                ctx.frame = ctx.next_frame
                ctx.next_frame += 1
                for _ in self._solve_literal(
                    A.Literal(expr=lit.expr, negated=lit.negated, line=lit.line),
                    env,
                    ctx,
                ):
                    solutions.append(dict(env))
            finally:
                ctx.undo(mark)
                ctx.frame = saved_frame
                for _ in range(pushed_input):
                    ctx.input_stack.pop()
                for _ in range(pushed_data):
                    ctx.data_overrides.pop()
            for snap in solutions:
                mark2 = ctx.mark()
                try:
                    for k, v in snap.items():
                        if k not in env or env[k] is not v:
                            ctx.bind(env, k, v)
                    yield
                finally:
                    ctx.undo(mark2)
            return

        expr = lit.expr
        if lit.negated:
            mark = ctx.mark()
            found = False
            try:
                for v in self._iter_expr(expr, env, ctx):
                    if v is not False:
                        found = True
                        break
            finally:
                ctx.undo(mark)
            if not found:
                yield
            return

        if isinstance(expr, A.SomeDecl):
            mark = ctx.mark()
            try:
                for n in expr.names:
                    ctx.bind(env, n, FRESH)
                yield
            finally:
                ctx.undo(mark)
            return

        if isinstance(expr, (A.Assign, A.Unify)):
            yield from self._solve_unify(
                expr.lhs, expr.rhs, env, ctx, assign=isinstance(expr, A.Assign)
            )
            return

        # plain expression literal: succeeds per binding with non-false value
        for v in self._iter_expr(expr, env, ctx):
            if v is not False:
                yield

    # ------------------------------------------------------------ unification

    def _solve_unify(
        self, lhs, rhs, env: dict, ctx: Ctx, assign: bool = False
    ) -> Iterator[None]:
        # `:=` always treats the lhs as a binding pattern — this is what lets
        # the reference's src_test.rego files shadow `input` with a local
        # (`input := {...}; ... with input as input`).
        lp = assign or self._is_pattern(lhs, env)
        rp = False if assign else self._is_pattern(rhs, env)
        if lp and not rp:
            for v in self._iter_term(rhs, env, ctx):
                mark = ctx.mark()
                try:
                    if self._unify_pattern(lhs, v, env, ctx):
                        yield
                finally:
                    ctx.undo(mark)
            return
        if rp and not lp:
            for v in self._iter_term(lhs, env, ctx):
                mark = ctx.mark()
                try:
                    if self._unify_pattern(rhs, v, env, ctx):
                        yield
                finally:
                    ctx.undo(mark)
            return
        if lp and rp:
            raise RegoError("cannot unify two non-ground terms")
        for a in self._iter_term(lhs, env, ctx):
            for b in self._iter_term(rhs, env, ctx):
                if rego_eq(a, b):
                    yield

    def _is_pattern(self, t, env: dict) -> bool:
        """True if t contains unbound vars bindable by pattern unification."""
        if isinstance(t, A.Var):
            if t.name in ("input", "data") and _is_unbound(env, t.name):
                return False
            return _is_unbound(env, t.name)
        if isinstance(t, A.ArrayLit):
            return any(self._is_pattern(x, env) for x in t.items)
        if isinstance(t, A.ObjectLit):
            return any(self._is_pattern(v, env) for _, v in t.items)
        return False

    def _unify_pattern(self, t, value, env: dict, ctx: Ctx) -> bool:
        if isinstance(t, A.Var):
            if _is_unbound(env, t.name):
                if not t.name.startswith("$wc"):
                    ctx.bind(env, t.name, value)
                return True
            return rego_eq(env[t.name], value)
        if isinstance(t, A.ArrayLit):
            if not isinstance(value, tuple) or len(value) != len(t.items):
                return False
            return all(
                self._unify_pattern(x, v, env, ctx)
                for x, v in zip(t.items, value)
            )
        if isinstance(t, A.ObjectLit):
            if not isinstance(value, FrozenDict) or len(value) != len(t.items):
                return False
            for k_t, v_t in t.items:
                ks = list(self._iter_term(k_t, env, ctx))
                if len(ks) != 1:
                    return False
                if ks[0] not in value:
                    return False
                if not self._unify_pattern(v_t, value[ks[0]], env, ctx):
                    return False
            return True
        for v in self._iter_term(t, env, ctx):
            return rego_eq(v, value)
        return False

    def _unify_pattern_all(self, terms, values, env: dict, ctx: Ctx) -> bool:
        return all(
            self._unify_pattern(t, v, env, ctx) for t, v in zip(terms, values)
        )

    # ------------------------------------------------------------ expressions

    def _iter_expr(self, expr, env: dict, ctx: Ctx) -> Iterator[Any]:
        if isinstance(expr, (A.Assign, A.Unify)):
            # expression position (e.g. inside `not`): succeed -> true
            for _ in self._solve_unify(expr.lhs, expr.rhs, env, ctx):
                yield True
            return
        yield from self._iter_term(expr, env, ctx)

    # ------------------------------------------------------------ terms

    def _iter_term(self, t, env: dict, ctx: Ctx) -> Iterator[Any]:
        if isinstance(t, A.Scalar):
            yield t.value
            return
        if isinstance(t, A.Var):
            yield from self._iter_var(t.name, env, ctx)
            return
        if isinstance(t, A.Ref):
            for base in self._iter_term(t.base, env, ctx):
                yield from self._walk_ref(base, t.args, 0, env, ctx)
            return
        if isinstance(t, A.Call):
            yield from self._iter_call(t, env, ctx)
            return
        if isinstance(t, A.BinOp):
            for a in self._iter_term(t.lhs, env, ctx):
                for b in self._iter_term(t.rhs, env, ctx):
                    v = _binop(t.op, a, b)
                    if v is not UNDEF:
                        yield v
            return
        if isinstance(t, A.UnaryMinus):
            for v in self._iter_term(t.term, env, ctx):
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    yield -v
            return
        if isinstance(t, A.ArrayLit):
            yield from self._iter_product(t.items, env, ctx, tuple)
            return
        if isinstance(t, A.SetLit):
            yield from self._iter_product(t.items, env, ctx, frozenset)
            return
        if isinstance(t, A.ObjectLit):
            keys = [k for k, _ in t.items]
            vals = [v for _, v in t.items]
            for kvs in self._iter_product(keys + vals, env, ctx, tuple):
                n = len(keys)
                yield FrozenDict(zip(kvs[:n], kvs[n:]))
            return
        if isinstance(t, A.ArrayCompr):
            out = []
            cenv = dict(env)
            mark = ctx.mark()
            try:
                for _ in self._solve(t.body, 0, cenv, ctx):
                    for v in self._iter_term(t.head, cenv, ctx):
                        out.append(v)
            finally:
                ctx.undo(mark)
            yield tuple(out)
            return
        if isinstance(t, A.SetCompr):
            acc = set()
            cenv = dict(env)
            mark = ctx.mark()
            try:
                for _ in self._solve(t.body, 0, cenv, ctx):
                    for v in self._iter_term(t.head, cenv, ctx):
                        acc.add(v)
            finally:
                ctx.undo(mark)
            yield frozenset(acc)
            return
        if isinstance(t, A.ObjectCompr):
            obj: dict = {}
            cenv = dict(env)
            mark = ctx.mark()
            try:
                for _ in self._solve(t.body, 0, cenv, ctx):
                    for k in self._iter_term(t.key, cenv, ctx):
                        for v in self._iter_term(t.value, cenv, ctx):
                            if k in obj and not rego_eq(obj[k], v):
                                raise RegoError(
                                    f"object comprehension: conflicting key {k!r}"
                                )
                            obj[k] = v
            finally:
                ctx.undo(mark)
            yield FrozenDict(obj)
            return
        raise RegoError(f"cannot evaluate term {t!r}")

    def _iter_product(self, terms, env, ctx, ctor) -> Iterator[Any]:
        vals: list = []

        def rec(i):
            if i == len(terms):
                yield ctor(vals)
                return
            for v in self._iter_term(terms[i], env, ctx):
                vals.append(v)
                try:
                    yield from rec(i + 1)
                finally:
                    vals.pop()

        yield from rec(0)

    def _iter_var(self, name: str, env: dict, ctx: Ctx) -> Iterator[Any]:
        v = env.get(name, _MISSING)
        if v is not _MISSING and v is not FRESH:
            yield v
            return
        if name == "input":
            if ctx.input is not None:
                yield ctx.input
            return  # no input document => undefined
        if name == "data":
            yield DataNode((), self.data)
            return
        pkg = ctx.pkg_stack[-1] if ctx.pkg_stack else ()
        rules = self._rules(pkg, name)
        if rules:
            if rules[0].kind == "function":
                raise RegoError(f"{name} is a function; it must be called")
            rv = self._rule_value(pkg, name, ctx)
            if rv is not UNDEF:
                yield rv
            return
        raise RegoError(f"unsafe variable {name!r} (line context: pkg {pkg})")

    # ------------------------------------------------------------ refs

    def _walk_ref(self, base, args, i, env: dict, ctx: Ctx) -> Iterator[Any]:
        if i == len(args):
            if isinstance(base, DataNode):
                yield self._materialize_node(base, ctx)
            else:
                yield base
            return
        arg = args[i]
        if isinstance(arg, A.Var) and _is_unbound(env, arg.name) and arg.name not in (
            "input",
            "data",
        ):
            wc = arg.name.startswith("$wc")
            for k, v in self._enumerate(base, ctx):
                mark = ctx.mark()
                try:
                    if not wc:
                        ctx.bind(env, arg.name, k)
                    yield from self._walk_ref(v, args, i + 1, env, ctx)
                finally:
                    ctx.undo(mark)
            return
        if self._is_pattern(arg, env):
            # composite pattern with unbound vars, e.g. the partial-set
            # membership general_violation[{"msg": msg, "field": "containers"}]
            # in library/general/containerlimits/src.rego
            for k, v in self._enumerate(base, ctx):
                mark = ctx.mark()
                try:
                    if self._unify_pattern(arg, k, env, ctx):
                        yield from self._walk_ref(v, args, i + 1, env, ctx)
                finally:
                    ctx.undo(mark)
            return
        for k in self._iter_term(arg, env, ctx):
            v = self._step(base, k, ctx)
            if v is not UNDEF:
                yield from self._walk_ref(v, args, i + 1, env, ctx)

    def _enumerate(self, base, ctx: Ctx):
        """Yield (key, value) children of a value or DataNode."""
        if isinstance(base, (FrozenDict, dict)):
            for k, v in base.items():
                yield k, v
        elif isinstance(base, tuple):
            for idx, v in enumerate(base):
                yield idx, v
        elif isinstance(base, frozenset):
            for m in sorted(base, key=sort_key):
                yield m, m
        elif isinstance(base, DataNode):
            seen = set()
            overrides = ctx.data_overrides[-1]
            plen = len(base.path)
            for opath in overrides:
                # overrides may mount deep below this node (`with
                # data.constraints.a.b.spec.match as {}` enumerated from
                # data.constraints) — surface the next path segment
                if len(opath) > plen and opath[:plen] == base.path:
                    k = opath[plen]
                    if k not in seen:
                        seen.add(k)
                        v = self._step(base, k, ctx)
                        if v is not UNDEF:
                            yield k, v
            pkg = self.packages.get(base.path)
            if pkg:
                for name, rules in pkg.items():
                    if rules[0].kind == "function" or name in seen:
                        continue
                    seen.add(name)
                    rv = self._rule_value(base.path, name, ctx)
                    if rv is not UNDEF:
                        yield name, rv
            for pfx in self._pkg_prefixes:
                if len(pfx) == plen + 1 and pfx[:plen] == base.path:
                    k = pfx[-1]
                    if k not in seen:
                        seen.add(k)
                        yield k, self._step(base, k, ctx)
            if isinstance(base.base, (dict, FrozenDict)):
                for k, v in base.base.items():
                    if k in seen:
                        continue
                    yield k, self._node_or_value(base.path + (k,), v)

    def _step(self, base, key, ctx: Ctx):
        if isinstance(base, DataNode):
            path = base.path + (key,)
            overrides = ctx.data_overrides[-1]
            if path in overrides:
                return overrides[path]
            pkg_rules = self.packages.get(base.path)
            if pkg_rules and key in pkg_rules:
                if pkg_rules[key][0].kind == "function":
                    raise RegoError(f"{key} is a function; it must be called")
                return self._rule_value(base.path, key, ctx)
            sub = _MISSING
            if isinstance(base.base, (dict, FrozenDict)):
                sub = base.base.get(key, _MISSING)
            if path in self._pkg_prefixes or any(
                p[: len(path)] == path for p in overrides
            ):
                return DataNode(path, sub if sub is not _MISSING else _MISSING)
            if sub is _MISSING:
                return UNDEF
            return self._node_or_value(path, sub)
        if isinstance(base, (FrozenDict, dict)):
            v = base.get(key, _MISSING)
            return UNDEF if v is _MISSING else v
        if isinstance(base, tuple):
            if isinstance(key, bool) or not isinstance(key, int):
                return UNDEF
            if 0 <= key < len(base):
                return base[key]
            return UNDEF
        if isinstance(base, frozenset):
            return key if key in base else UNDEF
        return UNDEF

    def _node_or_value(self, path: tuple, sub):
        # plain mutable dicts inside the store remain traversable; frozen
        # leaves are values
        if isinstance(sub, dict) and not isinstance(sub, FrozenDict):
            return DataNode(path, sub)
        return sub

    def _materialize_node(self, node: DataNode, ctx: Ctx):
        out = {}
        for k, v in self._enumerate(node, ctx):
            if isinstance(v, DataNode):
                v = self._materialize_node(v, ctx)
            out[k] = v
        return FrozenDict(out)

    # ------------------------------------------------------------ calls

    def _iter_call(self, t: A.Call, env: dict, ctx: Ctx) -> Iterator[Any]:
        pkg = ctx.pkg_stack[-1] if ctx.pkg_stack else ()
        fn_pkg = None
        fn_name = None
        if len(t.fn) == 1 and self._rules(pkg, t.fn[0]):
            fn_pkg, fn_name = pkg, t.fn[0]
        elif t.fn[0] == "data" and len(t.fn) > 2:
            cand_pkg, cand_name = tuple(t.fn[1:-1]), t.fn[-1]
            if self._rules(cand_pkg, cand_name):
                fn_pkg, fn_name = cand_pkg, cand_name

        if fn_pkg is not None:
            rules = self._rules(fn_pkg, fn_name)
            if rules[0].kind != "function":
                raise RegoError(f"{fn_name} is not a function")
            for argvals in self._iter_product(t.args, env, ctx, tuple):
                v = self._call_function(fn_pkg, fn_name, argvals, ctx)
                if v is not UNDEF:
                    yield v
            return

        if tuple(t.fn) == ("walk",):
            # multi-valued builtin: walk(x) enumerates every [path, value]
            # pair of the document, root first (OPA topdown/walk.go); the
            # common `[p, v] := walk(x)` form destructures the pairs.
            # Deliberately NOT in BUILTINS: codegen/device treat unknown
            # fns as Unsupported, falling back to this interpreter.
            for argvals in self._iter_product(t.args, env, ctx, tuple):
                yield from _walk_pairs(argvals[0])
            return

        fn = BUILTINS.get(t.fn)
        if fn is None:
            raise RegoError(f"unknown function {'.'.join(t.fn)}")
        for argvals in self._iter_product(t.args, env, ctx, tuple):
            try:
                v = fn(*argvals)
            except BuiltinError:
                continue
            except (TypeError, ValueError, KeyError, ZeroDivisionError):
                continue
            if v is not UNDEF:
                yield v


# ---------------------------------------------------------------- helpers


def _walk_pairs(v):
    stack = [((), v)]
    while stack:
        path, node = stack.pop()
        yield (path, node)
        if isinstance(node, FrozenDict):
            for k, x in node.items():
                stack.append((path + (k,), x))
        elif isinstance(node, tuple):
            for i, x in enumerate(node):
                stack.append((path + (i,), x))
        elif isinstance(node, frozenset):
            for x in node:
                stack.append((path + (x,), x))


def _binop(op: str, a, b):
    num_a = isinstance(a, (int, float)) and not isinstance(a, bool)
    num_b = isinstance(b, (int, float)) and not isinstance(b, bool)
    if op == "==":
        return rego_eq(a, b)
    if op == "!=":
        return not rego_eq(a, b)
    if op in ("<", "<=", ">", ">="):
        ka, kb = sort_key(a), sort_key(b)
        if op == "<":
            return ka < kb
        if op == "<=":
            return ka <= kb
        if op == ">":
            return ka > kb
        return ka >= kb
    if op == "+":
        if num_a and num_b:
            return a + b
        return UNDEF
    if op == "-":
        if num_a and num_b:
            return a - b
        if isinstance(a, frozenset) and isinstance(b, frozenset):
            return a - b
        return UNDEF
    if op == "*":
        if num_a and num_b:
            return a * b
        return UNDEF
    if op == "/":
        if num_a and num_b and b != 0:
            q = a / b
            if isinstance(a, int) and isinstance(b, int) and a % b == 0:
                return a // b
            return q
        return UNDEF
    if op == "%":
        if num_a and num_b and b != 0:
            return a % b
        return UNDEF
    if op == "|":
        if isinstance(a, frozenset) and isinstance(b, frozenset):
            return a | b
        return UNDEF
    if op == "&":
        if isinstance(a, frozenset) and isinstance(b, frozenset):
            return a & b
        return UNDEF
    return UNDEF


def _set_in(base, path: tuple, value):
    """Functional update of a frozen object at a path (for `with input.x as v`)."""
    if not path:
        return value
    obj = base if isinstance(base, FrozenDict) else FrozenDict()
    d = dict(obj)
    k = path[0]
    d[k] = _set_in(obj.get(k, FrozenDict()), path[1:], value)
    return FrozenDict(d)
