"""Per-template Python code generation — the host materialization JIT.

The tree-walking interpreter (interp.py) spends ~4-5k function calls per
violation evaluation on generic unification/backtracking machinery. For the
audit tail — materializing exact messages for every (object, constraint)
pair the device filter fired — that generic cost dominates the end-to-end
wall clock (the reference's analogous cost center is the topdown evaluator
behind pkg/audit/manager.go:250-271).

This module partially evaluates the interpreter for one template: each rule
body becomes straight-line Python (nested loops for iteration, `if` chains
for guards), sharing the interpreter's value model (frozen values from
utils/values.py), its builtins (builtins.py — identical sprintf/number
formatting), and its undefined semantics (an UNDEF sentinel threaded
through helper calls). Outputs are therefore bit-identical to the
interpreter's wherever compilation succeeds; anything outside the subset
raises Unsupported at compile time and the caller keeps the interpreter
path (the same fallback discipline as the device compiler, ir/compile.py).

Differential coverage: tests/test_codegen.py runs every reference library
template's harvested corpus through both paths and asserts equality.
"""

from __future__ import annotations

import operator as _operator
from typing import Any, Callable, Optional

from . import ast as A
from .builtins import BUILTINS, NONDETERMINISTIC, BuiltinError
from .interp import UNDEF, RegoError, _binop
from .safety import reorder_module
from ..utils.values import FrozenDict, rego_eq, sort_key


class Unsupported(Exception):
    pass


_MISS = object()  # fmemo miss sentinel (UNDEF is a legitimate result)


# ----------------------------------------------------------- runtime helpers


_SORTED_SETS: dict[int, tuple] = {}  # id(frozenset) -> (set, sorted tuple)


def _enum(base):
    """Value-only _enumerate (interp.py:696): (key, value) children."""
    if isinstance(base, dict):  # FrozenDict included
        return base.items()
    if isinstance(base, tuple):
        return enumerate(base)
    if isinstance(base, frozenset):
        # canonical order is hot (parameter sets re-enumerate per pair);
        # identity-keyed cache with a liveness check, bounded
        ent = _SORTED_SETS.get(id(base))
        if ent is not None and ent[0] is base:
            srt = ent[1]
        else:
            if len(_SORTED_SETS) > 4096:
                _SORTED_SETS.clear()
            srt = tuple(sorted(base, key=sort_key))
            _SORTED_SETS[id(base)] = (base, srt)
        return ((m, m) for m in srt)
    return ()


def _stepv(base, key):
    """Value-only _step (interp.py:743) with UNDEF propagation."""
    if isinstance(base, dict):
        v = base.get(key, UNDEF)
        return v
    if isinstance(base, tuple):
        if isinstance(key, bool) or not isinstance(key, int):
            return UNDEF
        if 0 <= key < len(base):
            return base[key]
        return UNDEF
    if isinstance(base, frozenset):
        return key if key in base else UNDEF
    return UNDEF


def _lookupk(base, k):
    """Keyed lookup with EXACTLY the semantics of enumerating `base` and
    filtering keys by rego_eq(k, key) — the join-reorder transform's
    contract (it replaces that enumeration). Differs from _stepv on
    bool-vs-number keys: rego_eq is type-aware while Python dict lookup
    aliases True with 1, so numeric/bool keys take the scan path."""
    if isinstance(base, dict):
        if isinstance(k, (bool, int, float)):
            for kk, vv in base.items():
                if rego_eq(k, kk):
                    return vv
            return UNDEF
        return base.get(k, UNDEF)
    if isinstance(base, tuple):
        if isinstance(k, bool):
            return UNDEF
        if isinstance(k, float):
            # builtins can produce integral floats at runtime (results
            # are not re-frozen); rego_eq(2.0, 2) matches index 2
            if not k.is_integer():
                return UNDEF
            k = int(k)
        if isinstance(k, int) and 0 <= k < len(base):
            return base[k]
        return UNDEF
    if isinstance(base, frozenset):
        if isinstance(k, (bool, int, float)):
            for m in base:
                if rego_eq(k, m):
                    return m
            return UNDEF
        return k if k in base else UNDEF
    return UNDEF


def _call(fn, *args):
    """Builtin call: undefined args / builtin errors -> undefined
    (mirrors _iter_call's except clauses, interp.py:822-830)."""
    for a in args:
        if a is UNDEF:
            return UNDEF
    try:
        return fn(*args)
    except BuiltinError:
        return UNDEF
    except (TypeError, ValueError, KeyError, ZeroDivisionError):
        return UNDEF


def _callu(fn, J, *args):
    """User-function call with undefined-argument propagation."""
    for a in args:
        if a is UNDEF:
            return UNDEF
    return fn(J, *args)


def _bin(op, a, b):
    if a is UNDEF or b is UNDEF:
        return UNDEF
    return _binop(op, a, b)


def _bin_eq(a, b):
    if a is UNDEF or b is UNDEF:
        return UNDEF
    return rego_eq(a, b)


def _bin_neq(a, b):
    if a is UNDEF or b is UNDEF:
        return UNDEF
    return not rego_eq(a, b)


def _bin_minus(a, b):
    """Mirrors _binop("-"): numeric difference or set difference."""
    if a is UNDEF or b is UNDEF:
        return UNDEF
    if isinstance(a, (int, float)) and not isinstance(a, bool) and \
            isinstance(b, (int, float)) and not isinstance(b, bool):
        return a - b
    if isinstance(a, frozenset) and isinstance(b, frozenset):
        return a - b
    return UNDEF


def _mk_bin_cmp(py_op):
    def f(a, b, _cmp=py_op):
        if a is UNDEF or b is UNDEF:
            return UNDEF
        return _cmp(sort_key(a), sort_key(b))
    return f


# codegen-time specialization of the hottest comparison/difference ops:
# one closure call instead of the generic op-string dispatch chain
# (identical semantics to _binop; everything else falls through to _bin)
_BIN_SPECIAL = {
    "==": _bin_eq,
    "!=": _bin_neq,
    "-": _bin_minus,
    "<": _mk_bin_cmp(_operator.lt),
    "<=": _mk_bin_cmp(_operator.le),
    ">": _mk_bin_cmp(_operator.gt),
    ">=": _mk_bin_cmp(_operator.ge),
}


def _neg(a):
    if isinstance(a, (int, float)) and not isinstance(a, bool):
        return -a
    return UNDEF


def _arr(*xs):
    for x in xs:
        if x is UNDEF:
            return UNDEF
    return xs


def _setl(*xs):
    for x in xs:
        if x is UNDEF:
            return UNDEF
    return frozenset(xs)


def _obj(*kv):
    for x in kv:
        if x is UNDEF:
            return UNDEF
    return FrozenDict(zip(kv[0::2], kv[1::2]))


# ----------------------------------------------------------------- compiler


def _hint_term_safe(t) -> bool:
    """True when a join-reorder pin expression cannot RAISE at runtime:
    vars, scalars, path refs with safe steps, and literal containers of
    the same. Calls (user functions can raise RegoError on multi-output
    conflicts), arithmetic (divide-by-zero), and comprehensions are
    excluded — path steps merely go UNDEF, which the guard handles."""
    if isinstance(t, (A.Var, A.Scalar)):
        return True
    if isinstance(t, A.Ref):
        return _hint_term_safe(t.base) and all(
            _hint_term_safe(a) for a in t.args)
    if isinstance(t, (A.ArrayLit, A.SetLit)):
        return all(_hint_term_safe(x) for x in t.items)
    if isinstance(t, A.ObjectLit):
        return all(_hint_term_safe(k) and _hint_term_safe(v)
                   for k, v in t.items)
    return False


class _NotDeterministic(Exception):
    """Internal: term needs loop emission (unbound ref args)."""


class _Emit:
    def __init__(self):
        self.lines: list[str] = []
        self._n = 0

    def w(self, ind: int, s: str) -> None:
        self.lines.append("    " * ind + s)

    def tmp(self) -> str:
        self._n += 1
        return f"_t{self._n}"

    def src(self) -> str:
        return "\n".join(self.lines)


class _Scope:
    """Static var -> python-name map; mirrors the runtime env exactly
    because literals are compiled in the safety-reordered evaluation
    order the interpreter uses."""

    def __init__(self, names: Optional[dict] = None):
        self.names = dict(names or {})
        self.fresh: set[str] = set()

    def child(self) -> "_Scope":
        c = _Scope(self.names)
        c.fresh = set(self.fresh)
        return c

    def bound(self, name: str) -> bool:
        return name in self.names


def _calls_nondeterministic(r: A.Rule) -> bool:
    found = False

    def walk(t) -> None:
        nonlocal found
        if found:
            return
        if isinstance(t, A.Call):
            if tuple(t.fn) in NONDETERMINISTIC:
                found = True
                return
            for a in t.args:
                walk(a)
        elif isinstance(t, A.Ref):
            walk(t.base)
            for a in t.args:
                walk(a)
        elif isinstance(t, A.BinOp):
            walk(t.lhs)
            walk(t.rhs)
        elif isinstance(t, A.UnaryMinus):
            walk(t.term)
        elif isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                walk(x)
        elif isinstance(t, A.ObjectLit):
            for k, v in t.items:
                walk(k)
                walk(v)
        elif isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
            for lit in t.body:
                if not isinstance(lit.expr, A.SomeDecl):
                    walk(lit.expr)
            for h in (getattr(t, "head", None), getattr(t, "key", None),
                      getattr(t, "value", None)):
                if h is not None:
                    walk(h)
        elif isinstance(t, (A.Assign, A.Unify)):
            walk(t.lhs)
            walk(t.rhs)

    for lit in r.body:
        if not isinstance(lit.expr, A.SomeDecl):
            walk(lit.expr)
    for h in (r.key, r.value):
        if h is not None:
            walk(h)
    return found


def _collect_arg_vars(t, into: set) -> None:
    if isinstance(t, A.Var):
        if not t.name.startswith("$wc"):
            into.add(t.name)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _collect_arg_vars(x, into)
    elif isinstance(t, A.ObjectLit):
        for _k, v in t.items:
            _collect_arg_vars(v, into)


def _term_vars(t, into: set) -> None:
    """All Var names + called function names appearing in a term."""
    if isinstance(t, A.Var):
        into.add(t.name)
    elif isinstance(t, A.Ref):
        _term_vars(t.base, into)
        for a in t.args:
            _term_vars(a, into)
    elif isinstance(t, A.Call):
        if len(t.fn) == 1:
            into.add(t.fn[0])
        else:
            into.add(t.fn[0])  # e.g. "data" roots mark impurity
        for a in t.args:
            _term_vars(a, into)
    elif isinstance(t, A.BinOp):
        _term_vars(t.lhs, into)
        _term_vars(t.rhs, into)
    elif isinstance(t, A.UnaryMinus):
        _term_vars(t.term, into)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _term_vars(x, into)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _term_vars(k, into)
            _term_vars(v, into)
    elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
        _term_vars(t.head, into)
        for lit in t.body:
            _term_vars(lit.expr, into)
    elif isinstance(t, A.ObjectCompr):
        _term_vars(t.key, into)
        _term_vars(t.value, into)
        for lit in t.body:
            _term_vars(lit.expr, into)
    elif isinstance(t, (A.Assign, A.Unify)):
        _term_vars(t.lhs, into)
        _term_vars(t.rhs, into)


def _sections_ok(module: A.Module) -> bool:
    """True when every `input` reference steps through a static
    "review"/"parameters" first segment (the hook contract), so the
    compiled evaluator can take the two sections as direct arguments —
    no per-call input-wrapper construction. A bare `input` anywhere
    (including as a pattern var) disables the optimization."""
    ok = True

    def walk(t) -> None:
        nonlocal ok
        if not ok:
            return
        if isinstance(t, A.Var):
            if t.name == "input":
                ok = False
            return
        if isinstance(t, A.Ref):
            if isinstance(t.base, A.Var) and t.base.name == "input":
                if not (t.args and isinstance(t.args[0], A.Scalar)
                        and t.args[0].value in ("review", "parameters")):
                    ok = False
                for a in t.args:
                    walk(a)
                return
            walk(t.base)
            for a in t.args:
                walk(a)
            return
        if isinstance(t, A.Call):
            for a in t.args:
                walk(a)
            return
        if isinstance(t, A.BinOp):
            walk(t.lhs)
            walk(t.rhs)
            return
        if isinstance(t, A.UnaryMinus):
            walk(t.term)
            return
        if isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                walk(x)
            return
        if isinstance(t, A.ObjectLit):
            for k, v in t.items:
                walk(k)
                walk(v)
            return
        if isinstance(t, (A.ArrayCompr, A.SetCompr)):
            walk(t.head)
            for lit in t.body:
                if not isinstance(lit.expr, A.SomeDecl):
                    walk(lit.expr)
            return
        if isinstance(t, A.ObjectCompr):
            walk(t.key)
            walk(t.value)
            for lit in t.body:
                if not isinstance(lit.expr, A.SomeDecl):
                    walk(lit.expr)
            return
        if isinstance(t, (A.Assign, A.Unify)):
            walk(t.lhs)
            walk(t.rhs)
            return

    for r in module.rules:
        for lit in r.body:
            if not isinstance(lit.expr, A.SomeDecl):
                walk(lit.expr)
        for h in (r.key, r.value):
            if h is not None:
                walk(h)
        for a in r.args:
            walk(a)
        if not ok:
            break
    return ok


def _is_const_term(t) -> bool:
    if isinstance(t, A.Scalar):
        return True
    if isinstance(t, (A.ArrayLit, A.SetLit)):
        return all(_is_const_term(x) for x in t.items)
    if isinstance(t, A.ObjectLit):
        return all(_is_const_term(k) and _is_const_term(v)
                   for k, v in t.items)
    return False


class ModuleCompiler:
    def __init__(self, module: A.Module):
        module = reorder_module(module)
        self.module = module
        self._sections = _sections_ok(module)
        self.rules: dict[str, list[A.Rule]] = {}
        for r in module.rules:
            self.rules.setdefault(r.name, []).append(r)
        # constant rules (pure literal values, e.g. unit tables like
        # containerlimits' unit_scale): folded to one module-level value
        # at compile time instead of re-materializing per evaluation,
        # and transparent to the arg-purity analysis so quantity-parsing
        # helpers that read them still memoize on their arguments
        self.const_rules = {
            name for name, rs in self.rules.items()
            if len(rs) == 1 and rs[0].kind == "complete"
            and not rs[0].body and not rs[0].is_default
            and rs[0].value is not None and _is_const_term(rs[0].value)}
        self.arg_pure = self._arg_pure_fns()
        self.em = _Emit()
        self.builtin_bindings: dict[tuple, str] = {}
        self.bin_bindings: dict[str, str] = {}
        self._pat_n = 0
        self._rmemo_n = 0  # review-pure comprehension memo slots
        self._pmemo_n = 0  # params-pure comprehension memo slots
        self._hmemo_n = 0  # head-witness memo slots
        # join-reorder bookkeeping: id(literal) -> (key var, pin expr);
        # _hint_refs pins the literal objects so ids stay valid
        self._key_hints: dict[int, tuple] = {}
        self._hint_refs: list = []
        self._hint_bind: dict[str, str] = {}
        # static input-path CSE: path tuple -> hoisted temp name, emitted
        # once at rule entry (pure _stepv chains, so unconditional
        # evaluation is safe — UNDEF just propagates)
        self._path_cache: Optional[dict] = None

    def _arg_pure_fns(self) -> set:
        """Functions whose result depends ONLY on their arguments: no
        input/data references, no calls to non-arg-pure user rules.
        Their calls memoize on the (frozen, hashable) argument tuple —
        the inventory-join hot loops re-apply the same projection
        function to the same inventory objects once per review, so a
        memo turns O(reviews × inventory) evaluations into O(inventory).
        """
        fns = {name: rules for name, rules in self.rules.items()
               if rules[0].kind == "function"}
        deps: dict[str, set] = {}
        for name, rules in fns.items():
            names: set = set()
            for r in rules:
                if any(lit.withs for lit in r.body):
                    names.add("input")  # `with`: treat as impure
                if _calls_nondeterministic(r):
                    names.add("input")  # time.now_ns etc: never memoize
                for lit in r.body:
                    _term_vars(lit.expr, names)
                if r.value is not None:
                    _term_vars(r.value, names)
                for a in r.args:
                    _term_vars(a, names)
            deps[name] = names
        pure = set(fns)
        changed = True
        while changed:
            changed = False
            for name in list(pure):
                names = deps[name]
                if "input" in names or "data" in names:
                    pure.discard(name)
                    changed = True
                    continue
                for n in names:
                    if n in self.rules and n not in fns:
                        if n in self.const_rules:
                            continue  # constants are pure by definition
                        pure.discard(name)  # reads a document rule
                        changed = True
                        break
                    if n in fns and n not in pure:
                        pure.discard(name)
                        changed = True
                        break
        return pure

    # ------------------------------------------------------------- naming

    def _py(self, scope: _Scope, name: str) -> str:
        pn = "v_" + name.replace("$", "_w_")
        scope.names[name] = pn
        scope.fresh.discard(name)
        return pn

    def _builtin(self, fn: tuple) -> str:
        b = self.builtin_bindings.get(fn)
        if b is None:
            b = "_b" + str(len(self.builtin_bindings))
            self.builtin_bindings[fn] = b
        return b

    def _bin_expr(self, op: str, a: str, b: str) -> str:
        """Binary-op call, specialized for the hot ops (_BIN_SPECIAL)."""
        if op not in _BIN_SPECIAL:
            return f"_bin({op!r}, {a}, {b})"
        bound = self.bin_bindings.get(op)
        if bound is None:
            bound = "_c" + str(len(self.bin_bindings))
            self.bin_bindings[op] = bound
        return f"{bound}({a}, {b})"

    # -------------------------------------------------------- deterministic

    def value(self, t, scope: _Scope, ind: int) -> str:
        """Python expression for a single-valued term; may pre-emit
        statements (comprehensions). Raises _NotDeterministic when the
        term iterates (unbound ref brackets)."""
        if isinstance(t, A.Scalar):
            return repr(t.value)
        if isinstance(t, A.Var):
            return self._var_value(t.name, scope)
        if isinstance(t, A.Ref):
            return self._ref_value(t, scope, ind)
        if isinstance(t, A.Call):
            return self._call_value(t, scope, ind)
        if isinstance(t, A.BinOp):
            a = self.value(t.lhs, scope, ind)
            b = self.value(t.rhs, scope, ind)
            return self._bin_expr(t.op, a, b)
        if isinstance(t, A.UnaryMinus):
            return f"_neg({self.value(t.term, scope, ind)})"
        if isinstance(t, A.ArrayLit):
            items = [self.value(x, scope, ind) for x in t.items]
            return f"_arr({', '.join(items)})"
        if isinstance(t, A.SetLit):
            items = [self.value(x, scope, ind) for x in t.items]
            return f"_setl({', '.join(items)})"
        if isinstance(t, A.ObjectLit):
            kv = []
            for k, v in t.items:
                kv.append(self.value(k, scope, ind))
                kv.append(self.value(v, scope, ind))
            return f"_obj({', '.join(kv)})"
        if isinstance(t, (A.SetCompr, A.ArrayCompr, A.ObjectCompr)):
            return self._compr(t, scope, ind)
        raise Unsupported(f"term {type(t).__name__}")

    def _var_value(self, name: str, scope: _Scope) -> str:
        if scope.bound(name):
            return scope.names[name]
        if name == "input":
            return "_J['input']"
        if name == "data":
            raise Unsupported("bare data reference")
        rules = self.rules.get(name)
        if rules:
            if rules[0].kind == "function":
                raise Unsupported(f"function {name} in value position")
            return f"rule_{name}(_J)"
        if name.startswith("$wc") or name in scope.fresh:
            raise _NotDeterministic()
        raise Unsupported(f"unbound var {name} in value position")

    def _ref_value(self, t: A.Ref, scope: _Scope, ind: int) -> str:
        args = list(t.args)
        cached = self._cached_input_prefix(t, scope)
        if cached is not None:
            base, args = cached
        elif isinstance(t.base, A.Var) and t.base.name == "data" and \
                not scope.bound("data"):
            if args and isinstance(args[0], A.Scalar) and \
                    args[0].value == "inventory":
                base = "_J['inv']"
                args = args[1:]
            else:
                raise Unsupported("data reference beyond inventory")
        else:
            base = self.value(t.base, scope, ind)
        for a in args:
            if isinstance(a, A.Var) and not scope.bound(a.name) and \
                    a.name not in ("input", "data"):
                raise _NotDeterministic()
            if self._is_static_pattern(a, scope):
                raise _NotDeterministic()
            base = f"_stepv({base}, {self.value(a, scope, ind)})"
        return base

    def _call_value(self, t: A.Call, scope: _Scope, ind: int) -> str:
        fn = tuple(t.fn)
        args = [self.value(a, scope, ind) for a in t.args]
        if len(fn) == 1 and fn[0] in self.rules:
            rules = self.rules[fn[0]]
            if rules[0].kind != "function":
                raise Unsupported(f"{fn[0]} is not a function")
            return f"_callu(fn_{fn[0]}, _J, {', '.join(args)})"
        if fn[0] == "data":
            raise Unsupported(f"data function call {fn}")
        if fn not in BUILTINS:
            raise Unsupported(f"unknown function {'.'.join(fn)}")
        b = self._builtin(fn)
        return f"_call({b}, {', '.join(args)})"

    # ------------------------------------------------- review-pure analysis

    def _review_pure(self, t, scope: _Scope) -> bool:
        return self._input_pure(t, scope, "review")

    def _params_pure(self, t, scope: _Scope) -> bool:
        return self._input_pure(t, scope, "parameters")

    def _input_pure(self, t, scope: _Scope, section: str) -> bool:
        """True when a comprehension's value depends ONLY on
        input.<section>: no outer-scope variable reads, no data/inventory
        refs, no user rule/function calls (they may read other input
        sections), and every input reference steps through <section>
        first. section="review" comprehensions are identical across the
        many constraints one review is evaluated against in an audit and
        memoize per review; section="parameters" comprehensions are
        identical across the many reviews one constraint sweeps and
        memoize per constraint."""
        outer = set(scope.names)

        def ok(x, bound: set) -> bool:
            if isinstance(x, A.Scalar):
                return True
            if isinstance(x, A.Var):
                if x.name in bound or x.name.startswith("$wc"):
                    return True
                # outer-scope binding (or a rule reference): impure
                return False
            if isinstance(x, A.Ref):
                if isinstance(x.base, A.Var) and x.base.name == "input" \
                        and "input" not in bound and "input" not in outer:
                    if not x.args or not (isinstance(x.args[0], A.Scalar)
                                          and x.args[0].value == section):
                        return False
                    return all(ok(a, bound) for a in x.args[1:])
                return ok(x.base, bound) and \
                    all(ok(a, bound) for a in x.args)
            if isinstance(x, A.Call):
                fn = tuple(x.fn)
                if fn not in BUILTINS or fn in NONDETERMINISTIC:
                    return False  # user/data fn or impure builtin
                return all(ok(a, bound) for a in x.args)
            if isinstance(x, A.BinOp):
                return ok(x.lhs, bound) and ok(x.rhs, bound)
            if isinstance(x, A.UnaryMinus):
                return ok(x.term, bound)
            if isinstance(x, (A.ArrayLit, A.SetLit)):
                return all(ok(i, bound) for i in x.items)
            if isinstance(x, A.ObjectLit):
                return all(ok(k, bound) and ok(v, bound)
                           for k, v in x.items)
            return False  # nested comprehensions etc.: be conservative

        def collect_vars(x, into: set) -> None:
            """All vars a pattern-position term could bind."""
            if isinstance(x, A.Var):
                into.add(x.name)
            elif isinstance(x, (A.ArrayLit, A.SetLit)):
                for i in x.items:
                    collect_vars(i, into)
            elif isinstance(x, A.ObjectLit):
                for _k, v in x.items:
                    collect_vars(v, into)
            elif isinstance(x, A.Ref):
                for a in x.args:
                    collect_vars(a, into)

        bound: set = set()
        body = getattr(t, "body", ())
        # first pass: everything the body can bind (iteration vars,
        # unification targets, some-decls) counts as locally bound
        for lit in body:
            if lit.withs:
                return False
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                bound.update(e.names)
            elif isinstance(e, (A.Assign, A.Unify)):
                collect_vars(e.lhs, bound)
                collect_vars(e.rhs, bound)
            else:
                collect_vars(e, bound)
        bound -= outer  # outer bindings shadow nothing here: reads of them
        # are what makes the comprehension constraint-dependent
        for lit in body:
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                continue
            if isinstance(e, (A.Assign, A.Unify)):
                if not (ok(e.lhs, bound) and ok(e.rhs, bound)):
                    return False
            elif not ok(e, bound):
                return False
        heads = [h for h in (getattr(t, "head", None),
                             getattr(t, "key", None),
                             getattr(t, "value", None)) if h is not None]
        return all(ok(h, bound) for h in heads)

    def _compr(self, t, scope: _Scope, ind: int) -> str:
        if self._review_pure(t, scope):
            slot = self._rmemo_n
            self._rmemo_n += 1
            out = self.em.tmp()
            self.em.w(ind, f"{out} = _J['rmemo'].get({slot})")
            self.em.w(ind, f"if {out} is None:")
            out2 = self._compr_emit(t, scope, ind + 1)
            self.em.w(ind + 1, f"{out} = {out2}")
            self.em.w(ind + 1, f"_J['rmemo'][{slot}] = {out}")
            return out
        if self._params_pure(t, scope):
            slot = self._pmemo_n
            self._pmemo_n += 1
            out = self.em.tmp()
            self.em.w(ind, f"{out} = _J['pmemo'].get({slot})")
            self.em.w(ind, f"if {out} is None:")
            out2 = self._compr_emit(t, scope, ind + 1)
            self.em.w(ind + 1, f"{out} = {out2}")
            self.em.w(ind + 1, f"_J['pmemo'][{slot}] = {out}")
            return out
        return self._compr_emit(t, scope, ind)

    def _compr_emit(self, t, scope: _Scope, ind: int) -> str:
        acc = self.em.tmp()
        sub = scope.child()
        body = self._schedule_body(t.body, set(scope.names))
        if isinstance(t, A.ObjectCompr):
            self.em.w(ind, f"{acc} = {{}}")

            def done(i):
                def kcont(j, kname):
                    def vcont(l, vname):
                        self.em.w(l, f"if {kname} in {acc} and not rego_eq("
                                     f"{acc}[{kname}], {vname}):")
                        self.em.w(l + 1,
                                  "raise RegoError('object comprehension: "
                                  "conflicting key')")
                        self.em.w(l, f"{acc}[{kname}] = {vname}")
                    self.iter_emit(t.value, sub, j, vcont)
                self.iter_emit(t.key, sub, i, kcont)
            self.solve(body, 0, sub, ind, done)
            out = self.em.tmp()
            self.em.w(ind, f"{out} = FrozenDict({acc})")
            return out
        ctor = "frozenset" if isinstance(t, A.SetCompr) else "tuple"
        self.em.w(ind, f"{acc} = []" if ctor == "tuple" else f"{acc} = set()")
        add = f"{acc}.append" if ctor == "tuple" else f"{acc}.add"

        def done2(i):
            self.iter_emit(t.head, sub, i,
                           lambda j, v: self.em.w(j, f"{add}({v})"))
        self.solve(body, 0, sub, ind, done2)
        out = self.em.tmp()
        self.em.w(ind, f"{out} = {ctor}({acc})")
        return out

    # ---------------------------------------------------------- iteration

    def iter_emit(self, t, scope: _Scope, ind: int,
                  cont: Callable[[int, str], None]) -> None:
        """Emit code yielding each value of term t; cont(ind, pyname) emits
        the per-value continuation. Values passed to cont are never UNDEF
        (mirrors _iter_term: undefined terms yield nothing)."""
        try:
            expr = self.value(t, scope, ind)
        except _NotDeterministic:
            self._iter_structural(t, scope, ind, cont)
            return
        v = self.em.tmp()
        self.em.w(ind, f"{v} = {expr}")
        if isinstance(t, A.Scalar):
            cont(ind, v)
            return
        self.em.w(ind, f"if {v} is not UNDEF:")
        cont(ind + 1, v)

    def _iter_structural(self, t, scope: _Scope, ind: int, cont) -> None:
        if isinstance(t, A.Ref):
            self._iter_ref(t, scope, ind, cont)
            return
        if isinstance(t, A.Call):
            self._iter_args(list(t.args), [], scope, ind,
                            lambda i, names: self._finish_call(
                                t, names, scope, i, cont))
            return
        if isinstance(t, A.BinOp):
            def fin(i, names):
                v = self.em.tmp()
                self.em.w(i, f"{v} = "
                             f"{self._bin_expr(t.op, names[0], names[1])}")
                self.em.w(i, f"if {v} is not UNDEF:")
                cont(i + 1, v)
            self._iter_args([t.lhs, t.rhs], [], scope, ind, fin)
            return
        if isinstance(t, (A.ArrayLit, A.SetLit)):
            ctor = "_arr" if isinstance(t, A.ArrayLit) else "_setl"

            def fin2(i, names):
                v = self.em.tmp()
                self.em.w(i, f"{v} = {ctor}({', '.join(names)})")
                cont(i, v)
            self._iter_args(list(t.items), [], scope, ind, fin2)
            return
        if isinstance(t, A.ObjectLit):
            terms = [k for k, _ in t.items] + [v for _, v in t.items]

            def fin3(i, names):
                n = len(t.items)
                kv = []
                for j in range(n):
                    kv.append(names[j])
                    kv.append(names[n + j])
                v = self.em.tmp()
                self.em.w(i, f"{v} = _obj({', '.join(kv)})")
                cont(i, v)
            self._iter_args(terms, [], scope, ind, fin3)
            return
        raise Unsupported(f"iterating term {type(t).__name__}")

    def _finish_call(self, t: A.Call, argnames, scope, ind, cont):
        fn = tuple(t.fn)
        if len(fn) == 1 and fn[0] in self.rules:
            if self.rules[fn[0]][0].kind != "function":
                raise Unsupported(f"{fn[0]} is not a function")
            expr = f"_callu(fn_{fn[0]}, _J, {', '.join(argnames)})"
        elif fn in BUILTINS:
            expr = f"_call({self._builtin(fn)}, {', '.join(argnames)})"
        else:
            raise Unsupported(f"unknown function {'.'.join(fn)}")
        v = self.em.tmp()
        self.em.w(ind, f"{v} = {expr}")
        self.em.w(ind, f"if {v} is not UNDEF:")
        cont(ind + 1, v)

    def _iter_args(self, terms, names, scope, ind, fin) -> None:
        """Cross-product iteration of argument terms (interp _iter_product)."""
        if not terms:
            fin(ind, names)
            return
        self.iter_emit(terms[0], scope, ind,
                       lambda i, v: self._iter_args(
                           terms[1:], names + [v], scope, i, fin))

    def _iter_ref(self, t: A.Ref, scope: _Scope, ind: int, cont) -> None:
        args = list(t.args)
        cached = self._cached_input_prefix(t, scope)
        if cached is not None:
            base, args = cached
            if not args:
                # whole ref is the hoisted path: keep iter_emit's
                # UNDEF-yields-nothing contract
                self.em.w(ind, f"if {base} is not UNDEF:")
                cont(ind + 1, base)
                return
            self._walk(base, args, scope, ind, cont)
            return
        if isinstance(t.base, A.Var) and t.base.name == "data" and \
                not scope.bound("data"):
            if args and isinstance(args[0], A.Scalar) and \
                    args[0].value == "inventory":
                base = self.em.tmp()
                self.em.w(ind, f"{base} = _J['inv']")
                self._walk(base, args[1:], scope, ind, cont)
                return
            raise Unsupported("data reference beyond inventory")
        self.iter_emit(t.base, scope, ind,
                       lambda i, b: self._walk(b, args, scope, i, cont))

    def _walk(self, base: str, args, scope: _Scope, ind: int, cont) -> None:
        if not args:
            cont(ind, base)
            return
        a = args[0]
        unbound_var = (isinstance(a, A.Var)
                       and not scope.bound(a.name)
                       and a.name not in ("input", "data"))
        if unbound_var and a.name in self._hint_bind:
            # join-reorder hint: a later equality pins this key var, so
            # replace the enumeration with one keyed lookup
            te = self._hint_bind.pop(a.name)
            pn = self._py(scope, a.name)
            self.em.w(ind, f"{pn} = {te}")
            v = self.em.tmp()
            self.em.w(ind, f"{v} = _lookupk({base}, {pn})")
            self.em.w(ind, f"if {v} is not UNDEF:")
            self._walk(v, args[1:], scope, ind + 1, cont)
            return
        if unbound_var:
            k = self.em.tmp()
            v = self.em.tmp()
            self.em.w(ind, f"for {k}, {v} in _enum({base}):")
            sub_ind = ind + 1
            if not a.name.startswith("$wc"):
                pn = self._py(scope, a.name)
                self.em.w(sub_ind, f"{pn} = {k}")
            self._walk(v, args[1:], scope, sub_ind, cont)
            return
        if self._is_static_pattern(a, scope):
            k = self.em.tmp()
            v = self.em.tmp()
            self.em.w(ind, f"for {k}, {v} in _enum({base}):")
            self.pattern(a, k, scope, ind + 1,
                         lambda i: self._walk(v, args[1:], scope, i, cont))
            return
        key = self.value(a, scope, ind)
        nxt = self.em.tmp()
        self.em.w(ind, f"{nxt} = _stepv({base}, {key})")
        self.em.w(ind, f"if {nxt} is not UNDEF:")
        self._walk(nxt, args[1:], scope, ind + 1, cont)

    # ------------------------------------------------------------ patterns

    def _is_static_pattern(self, t, scope: _Scope) -> bool:
        """Static mirror of interp._is_pattern over the tracked scope."""
        if isinstance(t, A.Var):
            if t.name in ("input", "data") and not scope.bound(t.name):
                return False
            return not scope.bound(t.name)
        if isinstance(t, A.ArrayLit):
            return any(self._is_static_pattern(x, scope) for x in t.items)
        if isinstance(t, A.ObjectLit):
            return any(self._is_static_pattern(v, scope)
                       for _, v in t.items)
        return False

    def pattern(self, t, val: str, scope: _Scope, ind: int, cont) -> None:
        """Emit unification of pattern t against value `val`
        (mirrors _unify_pattern, interp.py:487)."""
        if isinstance(t, A.Var):
            if not scope.bound(t.name):
                if t.name.startswith("$wc"):
                    cont(ind)
                    return
                pn = self._py(scope, t.name)
                self.em.w(ind, f"{pn} = {val}")
                cont(ind)
                return
            self.em.w(ind, f"if rego_eq({scope.names[t.name]}, {val}):")
            cont(ind + 1)
            return
        if isinstance(t, A.ArrayLit):
            n = len(t.items)
            self.em.w(ind, f"if isinstance({val}, tuple) and "
                           f"len({val}) == {n}:")
            def chain(i, idx):
                if idx == n:
                    cont(i)
                    return
                el = self.em.tmp()
                self.em.w(i, f"{el} = {val}[{idx}]")
                self.pattern(t.items[idx], el, scope, i,
                             lambda j: chain(j, idx + 1))
            chain(ind + 1, 0)
            return
        if isinstance(t, A.ObjectLit):
            n = len(t.items)
            self.em.w(ind, f"if isinstance({val}, FrozenDict) and "
                           f"len({val}) == {n}:")
            items = list(t.items)

            def ochain(i, idx):
                if idx == n:
                    cont(i)
                    return
                k_t, v_t = items[idx]
                kx = self.value(k_t, scope, i)
                kv = self.em.tmp()
                self.em.w(i, f"{kv} = {kx}")
                self.em.w(i, f"if {kv} in {val}:")
                el = self.em.tmp()
                self.em.w(i + 1, f"{el} = {val}[{kv}]")
                self.pattern(v_t, el, scope, i + 1,
                             lambda j: ochain(j, idx + 1))
            ochain(ind + 1, 0)
            return
        # ground term: compare (final case of _unify_pattern)
        expr = self.value(t, scope, ind)
        self.em.w(ind, f"if rego_eq({expr}, {val}):")
        cont(ind + 1)

    # ------------------------------------------------------------- literals

    def solve(self, lits, i: int, scope: _Scope, ind: int, done) -> None:
        """Emit body literals [i:], then done(ind) at full success."""
        if i == len(lits):
            done(ind)
            return
        lit = lits[i]
        nxt = lambda j: self.solve(lits, i + 1, scope, j, done)
        if lit.withs:
            raise Unsupported("with modifier")
        expr = lit.expr
        hint = self._key_hints.get(id(lit))
        if hint is not None and not lit.negated:
            k_name, e_term = hint
            # the pin expression evaluates BEFORE (and regardless of)
            # the enumeration producing bindings, so it must be unable
            # to raise: a user-function call erroring here would
            # surface where the interpreter, evaluating the (possibly
            # empty) enumeration first, produces no violation at all
            if not scope.bound(k_name) and _hint_term_safe(e_term):
                try:
                    e_expr = self.value(e_term, scope, ind)
                except (_NotDeterministic, Unsupported):
                    e_expr = None
                if e_expr is not None:
                    te = self.em.tmp()
                    self.em.w(ind, f"{te} = {e_expr}")
                    self.em.w(ind, f"if {te} is not UNDEF:")
                    ind += 1
                    self._hint_bind[k_name] = te
        if lit.negated:
            self._emit_negation(expr, scope, ind, nxt)
            return
        if isinstance(expr, A.SomeDecl):
            for n in expr.names:
                scope.fresh.add(n)
                scope.names.pop(n, None)
            nxt(ind)
            return
        if isinstance(expr, (A.Assign, A.Unify)):
            self._emit_unify(expr, scope, ind, nxt)
            return
        # plain expression literal: succeeds per non-false value
        self.iter_emit(expr, scope, ind, lambda j, v: (
            self.em.w(j, f"if {v} is not False:"), nxt(j + 1)))

    def _emit_negation(self, expr, scope: _Scope, ind: int, nxt) -> None:
        fn = self.em.tmp()
        self.em.w(ind, f"def _ng{fn}():")
        sub = scope.child()
        body_ind = ind + 1
        wrote = len(self.em.lines)
        if isinstance(expr, (A.Assign, A.Unify)):
            # expression position: unify success -> exists
            self._emit_unify(expr, sub, body_ind,
                             lambda j: self.em.w(j, "return True"))
        else:
            self.iter_emit(expr, sub, body_ind, lambda j, v: (
                self.em.w(j, f"if {v} is not False:"),
                self.em.w(j + 1, "return True")))
        if len(self.em.lines) == wrote:
            self.em.w(body_ind, "pass")
        self.em.w(body_ind, "return False")
        self.em.w(ind, f"if not _ng{fn}():")
        nxt(ind + 1)

    def _emit_unify(self, expr, scope: _Scope, ind: int, nxt) -> None:
        assign = isinstance(expr, A.Assign)
        lhs, rhs = expr.lhs, expr.rhs
        lp = assign or self._is_static_pattern(lhs, scope)
        rp = (not assign) and self._is_static_pattern(rhs, scope)
        if lp and rp:
            raise Unsupported("unifying two non-ground terms")
        if lp:
            self.iter_emit(rhs, scope, ind, lambda i, v:
                           self.pattern(lhs, v, scope, i, nxt))
            return
        if rp:
            self.iter_emit(lhs, scope, ind, lambda i, v:
                           self.pattern(rhs, v, scope, i, nxt))
            return
        def both(i, a):
            self.iter_emit(rhs, scope, i, lambda j, b: (
                self.em.w(j, f"if rego_eq({a}, {b}):"), nxt(j + 1)))
        self.iter_emit(lhs, scope, ind, both)

    # ------------------------------------------------ input-path hoisting

    def _collect_input_paths(self, rules) -> list[tuple]:
        """All maximal static input.<scalars...> prefixes referenced by
        the given clauses (including inside comprehensions and negation),
        for hoisting to one _stepv chain at rule entry. The chains are
        pure and total (UNDEF propagates through _stepv), so evaluating
        them unconditionally preserves semantics exactly."""
        found: set = set()

        def ref(t) -> None:
            if isinstance(t.base, A.Var) and t.base.name == "input":
                pre = []
                for a in t.args:
                    if isinstance(a, A.Scalar) and isinstance(
                            a.value, (str, int, bool)):
                        pre.append(a.value)
                    else:
                        break
                if pre:
                    found.add(tuple(pre))
            walk(t.base)
            for a in t.args:
                walk(a)

        def walk(t) -> None:
            if isinstance(t, A.Ref):
                ref(t)
            elif isinstance(t, A.Call):
                for a in t.args:
                    walk(a)
            elif isinstance(t, A.BinOp):
                walk(t.lhs)
                walk(t.rhs)
            elif isinstance(t, A.UnaryMinus):
                walk(t.term)
            elif isinstance(t, (A.ArrayLit, A.SetLit)):
                for x in t.items:
                    walk(x)
            elif isinstance(t, A.ObjectLit):
                for k, v in t.items:
                    walk(k)
                    walk(v)
            elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
                walk(t.head)
                for lit in t.body:
                    walk_lit(lit)
            elif isinstance(t, A.ObjectCompr):
                walk(t.key)
                walk(t.value)
                for lit in t.body:
                    walk_lit(lit)
            elif isinstance(t, (A.Assign, A.Unify)):
                walk(t.lhs)
                walk(t.rhs)

        def walk_lit(lit) -> None:
            if isinstance(lit.expr, A.SomeDecl):
                return
            walk(lit.expr)

        for r in rules:
            for lit in r.body:
                walk_lit(lit)
            for h in (r.key, r.value):
                if h is not None:
                    walk(h)
            for a in r.args:
                walk(a)
        # type-aware order: int and str segments may share a position
        return sorted(found, key=lambda p: [repr(s) for s in p])

    def _emit_path_cache(self, rules, ind: int) -> None:
        """Emit the hoisted _stepv chains. Maximal review-/parameters-
        rooted paths are additionally memoized in rmemo/pmemo — the
        audit fan-out calls the evaluator ~|constraints| times per
        review, so a per-review (resp. per-constraint) dict get replaces
        the whole chain on every call after the first."""
        self._path_cache = {}
        if self._sections:
            self._path_cache[("review",)] = "_J['rev']"
            self._path_cache[("parameters",)] = "_J['par']"
        for path in self._collect_input_paths(rules):
            if path in self._path_cache:
                continue
            memo = None
            if len(path) >= 2 and path[0] == "review":
                memo = "rmemo"
            elif len(path) >= 2 and path[0] == "parameters":
                memo = "pmemo"
            if memo is not None:
                t = self.em.tmp()
                key = ("p",) + path  # typed tuple: 1 and "1" stay distinct
                self.em.w(ind, f"{t} = _J[{memo!r}].get({key!r}, _MISS)")
                self.em.w(ind, f"if {t} is _MISS:")
                root = self._path_cache.get((path[0],), None)
                chain = (root if root is not None
                         else f"_stepv(_J['input'], {path[0]!r})")
                for seg in path[1:]:
                    chain = f"_stepv({chain}, {seg!r})"
                self.em.w(ind + 1, f"{t} = {chain}")
                self.em.w(ind + 1, f"_J[{memo!r}][{key!r}] = {t}")
                self._path_cache[path] = t
                continue
            for ln in range(1, len(path) + 1):
                pre = path[:ln]
                if pre in self._path_cache:
                    continue
                parent = ("_J['input']" if ln == 1
                          else self._path_cache[pre[:-1]])
                t = self.em.tmp()
                self.em.w(ind, f"{t} = _stepv({parent}, {pre[-1]!r})")
                self._path_cache[pre] = t

    def _cached_input_prefix(self, t: A.Ref, scope: _Scope):
        """(temp name, remaining args) when this ref starts with a
        hoisted static input path (longest cached prefix wins); None
        otherwise."""
        if self._path_cache is None or scope.bound("input"):
            return None
        if not (isinstance(t.base, A.Var) and t.base.name == "input"):
            return None
        run: list = []
        for a in t.args:
            if isinstance(a, A.Scalar) and isinstance(a.value,
                                                      (str, int, bool)):
                run.append(a.value)
            else:
                break
        for ln in range(len(run), 0, -1):
            hit = self._path_cache.get(tuple(run[:ln]))
            if hit is not None:
                return hit, list(t.args[ln:])
        return None

    # ----------------------------------------------------- join reorder

    def _names_unbound(self, t, bound: set) -> set:
        """Over-approximated new names a term could bind (every unbound
        non-root, non-rule, non-wildcard name appearing anywhere)."""
        allv: set = set()
        _term_vars(t, allv)
        return {v for v in allv
                if v not in bound and not v.startswith("$wc")
                and v not in ("input", "data") and v not in self.rules}

    def _expr_read_vars(self, t) -> set:
        allv: set = set()
        _term_vars(t, allv)
        builtin1 = {fn[0] for fn in BUILTINS}
        return {v for v in allv
                if not v.startswith("$wc") and v not in ("input", "data")
                and v not in self.rules and v not in builtin1}

    def _enum_key_var(self, lit, bound: set) -> Optional[str]:
        """Leftmost unbound non-wildcard Var bracket arg of the literal's
        generator ref — the candidate join key."""
        if lit.negated or lit.withs:
            return None
        e = lit.expr
        refs = []
        if isinstance(e, (A.Assign, A.Unify)):
            for side in (e.rhs, e.lhs):
                if isinstance(side, A.Ref):
                    refs.append(side)
        elif isinstance(e, A.Ref):
            refs.append(e)
        for r in refs:
            for a in r.args:
                if isinstance(a, A.Var) and not a.name.startswith("$wc") \
                        and a.name not in bound \
                        and a.name not in ("input", "data"):
                    return a.name
        return None

    def _movable_generator(self, lit, bound: set) -> Optional[set]:
        """EXACT bind set of a self-contained hoistable generator —
        Assign/Unify of a fresh var (or flat var tuple) against a Ref
        rooted at input/data whose bracket args are scalars, already-
        bound vars, wildcards, or fresh vars — else None."""
        if lit.negated or lit.withs:
            return None
        e = lit.expr
        if not isinstance(e, (A.Assign, A.Unify)):
            return None
        for pat, refside in ((e.lhs, e.rhs), (e.rhs, e.lhs)):
            if not isinstance(refside, A.Ref):
                continue
            base = refside.base
            if not (isinstance(base, A.Var) and base.name in ("input",
                                                              "data")):
                continue
            if isinstance(pat, A.Var):
                pv = [pat.name]
            elif isinstance(pat, A.ArrayLit) and \
                    all(isinstance(x, A.Var) for x in pat.items):
                pv = [x.name for x in pat.items]
            else:
                continue
            if any(n in bound for n in pv):
                continue
            binds = {n for n in pv if not n.startswith("$wc")}
            ok = True
            for a in refside.args:
                if isinstance(a, A.Scalar):
                    continue
                if isinstance(a, A.Var):
                    if a.name.startswith("$wc") or a.name in bound:
                        continue
                    binds.add(a.name)
                    continue
                ok = False
                break
            if ok:
                return binds
        return None

    def _schedule_body(self, lits, bound0=()) -> list:
        """Equality-driven join reorder (sideways information passing):
        when a generator enumerates base[k] only for a later equality to
        pin k to an expression E, hoist the one self-contained generator
        that makes E computable and mark the enumeration for conversion
        to a keyed lookup (_lookupk keeps enumeration-filter typing; the
        pin literal stays in place as a now-trivial check). The classic
        shape is the review-dict x parameters join

            value := input.review...labels[key]
            expected := input.parameters.labels[_]
            expected.key == key

        which drops from O(|labels| x |params|) iterations per pair to
        O(|params|) lookups. Solution sets are order-independent
        (conjunctive body), so the transform is semantics-preserving."""
        out = list(lits)
        bound: set = set(bound0)
        g = 0
        guard = 0
        while g < len(out):
            guard += 1
            if guard > 10 * len(out) + 10:
                return list(lits)  # paranoid: never loop forever
            lit = out[g]
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                bound -= set(e.names)
                g += 1
                continue
            k = None
            if id(lit) not in self._key_hints:
                k = self._enum_key_var(lit, bound)
            if k is None:
                if not lit.negated:
                    bound |= self._names_unbound(e, bound)
                g += 1
                continue
            # find a pin: a later equality between Var(k) and a k-free E
            pin = None
            for j in range(g + 1, len(out)):
                lj = out[j]
                if lj.negated or lj.withs:
                    continue
                ej = lj.expr
                sides = None
                if isinstance(ej, A.BinOp) and ej.op == "==":
                    sides = (ej.lhs, ej.rhs)
                elif isinstance(ej, A.Unify):
                    sides = (ej.lhs, ej.rhs)
                if not sides:
                    continue
                for a, b in (sides, sides[::-1]):
                    if isinstance(a, A.Var) and a.name == k and \
                            k not in self._expr_read_vars(b):
                        pin = (j, b)
                        break
                if pin:
                    break
            if pin is None:
                bound |= self._names_unbound(e, bound)
                g += 1
                continue
            e_idx, E = pin
            need = self._expr_read_vars(E) - bound
            if not need:
                # E already computable here: mark the keyed lookup
                self._key_hints[id(lit)] = (k, E)
                self._hint_refs.append(lit)
                bound |= self._names_unbound(e, bound)
                g += 1
                continue
            # find ONE hoistable generator in (g, e_idx) covering `need`,
            # whose binds don't collide with anything in between
            moved = False
            for s in range(g + 1, e_idx):
                binds = self._movable_generator(out[s], bound)
                if binds is None or not need <= binds:
                    continue
                between_binds: set = set()
                for m in range(g, s):
                    if not out[m].negated and \
                            not isinstance(out[m].expr, A.SomeDecl):
                        between_binds |= self._names_unbound(
                            out[m].expr, bound)
                if binds & between_binds:
                    continue
                mv = out.pop(s)
                out.insert(g, mv)
                moved = True
                break
            if not moved:
                bound |= self._names_unbound(e, bound)
                g += 1
            # when moved: reprocess position g (now the hoisted
            # generator); the enumeration gets its hint on the revisit

        return out

    # ------------------------------------------------- head-witness memo

    def _scan_lit(self, lit, bound: set) -> dict:
        """Static facts about one body literal for the head-memo planner:
        ok     — var-only: no direct input/data/document-rule reads, no
                 non-arg-pure user calls (so its value is a pure function
                 of the variables it reads);
        enum   — needs loop emission (enumerates; can't sit in the
                 memoized suffix);
        reads  — already-bound vars it consumes;
        binds  — vars it binds (for the forward bound-set simulation).
        Conservative: anything unrecognized clears ok — the planner then
        simply declines to memoize, never miscompiles."""
        s = {"ok": True, "enum": False, "reads": set(), "binds": set()}
        if lit.withs:
            s["ok"] = False
            return s
        e = lit.expr
        if isinstance(e, A.SomeDecl):
            s["ok"] = False  # scope boundary; forward sim unbinds names
            return s

        def pat_vars(t, into: set) -> None:
            if isinstance(t, A.Var):
                into.add(t.name)
            elif isinstance(t, (A.ArrayLit, A.SetLit)):
                for i in t.items:
                    pat_vars(i, into)
            elif isinstance(t, A.ObjectLit):
                for _k, v in t.items:
                    pat_vars(v, into)
            elif isinstance(t, A.Ref):
                for a in t.args:
                    pat_vars(a, into)

        def val(t, local: frozenset, quiet: bool) -> None:
            # `quiet`: inside a deterministic sub-value (comprehension or
            # negation) whose internal enumeration doesn't make the
            # literal itself enumerate
            if not s["ok"]:
                return
            if isinstance(t, A.Scalar):
                return
            if isinstance(t, A.Var):
                n = t.name
                if n in local:
                    return
                if n in ("input", "data"):
                    s["ok"] = False
                    return
                if n in bound:
                    s["reads"].add(n)
                    return
                if n in self.rules:
                    s["ok"] = False  # document rule / fn value reference
                    return
                if quiet:
                    return  # locally-bound inside compr/negation
                s["enum"] = True
                if not n.startswith("$wc"):
                    s["binds"].add(n)
                return
            if isinstance(t, A.Ref):
                if isinstance(t.base, A.Var) and \
                        t.base.name in ("input", "data") and \
                        t.base.name not in local and t.base.name not in bound:
                    s["ok"] = False
                    return
                val(t.base, local, quiet)
                for a in t.args:
                    if isinstance(a, A.Var) and a.name not in local and \
                            a.name not in bound and \
                            a.name not in ("input", "data"):
                        if not quiet:
                            s["enum"] = True
                            if not a.name.startswith("$wc"):
                                s["binds"].add(a.name)
                        local = local | {a.name}
                        continue
                    pv: set = set()
                    pat_vars(a, pv)
                    unb = {v for v in pv if v not in local and v not in bound}
                    if unb and not isinstance(a, A.Var):
                        # static pattern bracket: enumerates + binds
                        if not quiet:
                            s["enum"] = True
                            s["binds"] |= {v for v in unb
                                           if not v.startswith("$wc")}
                        local = local | unb
                        continue
                    val(a, local, quiet)
                return
            if isinstance(t, A.Call):
                fn = tuple(t.fn)
                if len(fn) == 1 and fn[0] in self.rules:
                    if fn[0] not in self.arg_pure:
                        s["ok"] = False
                        return
                elif fn[0] == "data" or fn not in BUILTINS or \
                        fn in NONDETERMINISTIC:
                    s["ok"] = False
                    return
                for a in t.args:
                    val(a, local, quiet)
                return
            if isinstance(t, A.BinOp):
                val(t.lhs, local, quiet)
                val(t.rhs, local, quiet)
                return
            if isinstance(t, A.UnaryMinus):
                val(t.term, local, quiet)
                return
            if isinstance(t, (A.ArrayLit, A.SetLit)):
                for x in t.items:
                    val(x, local, quiet)
                return
            if isinstance(t, A.ObjectLit):
                for k, v in t.items:
                    val(k, local, quiet)
                    val(v, local, quiet)
                return
            if isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
                # a comprehension is a deterministic value; its body may
                # enumerate internally over locally-bound vars
                lb: set = set()
                for l2 in t.body:
                    if l2.withs:
                        s["ok"] = False
                        return
                    e2 = l2.expr
                    if isinstance(e2, A.SomeDecl):
                        lb.update(e2.names)
                        continue
                    if isinstance(e2, (A.Assign, A.Unify)):
                        pat_vars(e2.lhs, lb)
                        pat_vars(e2.rhs, lb)
                    else:
                        pat_vars(e2, lb)
                lb -= bound  # outer-bound names are reads, not locals
                inner = local | frozenset(lb)
                for l2 in t.body:
                    e2 = l2.expr
                    if isinstance(e2, A.SomeDecl):
                        continue
                    if isinstance(e2, (A.Assign, A.Unify)):
                        val(e2.lhs, inner, True)
                        val(e2.rhs, inner, True)
                    else:
                        val(e2, inner, True)
                for h in (getattr(t, "head", None), getattr(t, "key", None),
                          getattr(t, "value", None)):
                    if h is not None:
                        val(h, inner, True)
                return
            s["ok"] = False

        if lit.negated:
            # negation exports no bindings and is deterministic overall
            if isinstance(e, (A.Assign, A.Unify)):
                val(e.lhs, frozenset(), True)
                val(e.rhs, frozenset(), True)
            else:
                val(e, frozenset(), True)
            s["binds"] = set()
            return s

        def complete_binds() -> None:
            # the forward bound-set simulation must never UNDER-report
            # binds (a var the emitter binds but the simulation missed
            # could silently drop out of a memo key). Over-reporting is
            # safe: it only widens the key or trips the emission
            # fallback. So fold in every previously-unbound name
            # appearing anywhere in the literal.
            allv: set = set()
            _term_vars(e, allv)
            s["binds"] |= {v for v in allv
                           if v not in bound and not v.startswith("$wc")
                           and v not in ("input", "data")
                           and v not in self.rules}
        if isinstance(e, (A.Assign, A.Unify)):
            lv: set = set()
            pat_vars(e.lhs, lv)
            rv: set = set()
            pat_vars(e.rhs, rv)
            lhs_unb = {v for v in lv if v not in bound}
            rhs_unb = {v for v in rv if v not in bound}
            if isinstance(e, A.Assign) or not lhs_unb or not rhs_unb:
                patside, valside = (e.lhs, e.rhs)
                if not isinstance(e, A.Assign) and rhs_unb and not lhs_unb:
                    patside, valside = (e.rhs, e.lhs)
                val(valside, frozenset(), False)
                pv: set = set()
                pat_vars(patside, pv)
                unb = {v for v in pv if v not in bound}
                if isinstance(patside, A.Var) or not unb:
                    # plain binder (or ground-ground compare): deterministic
                    s["binds"] |= {v for v in unb if not v.startswith("$wc")}
                    s["reads"] |= pv & bound
                    if not isinstance(patside, A.Var):
                        val(patside, frozenset(unb), False)
                else:
                    # destructuring pattern: conservative, exclude
                    s["ok"] = False
            else:
                s["ok"] = False  # two non-ground sides
            complete_binds()
            return s
        val(e, frozenset(), False)
        complete_binds()
        return s

    def _head_memo_plan(self, body_lits, head_key):
        """Plan the head-witness memo for a partial-set rule: find the
        maximal suffix of body literals that is deterministic and
        var-only (see _scan_lit), so (suffix + head) is a pure function
        of the outer vars V flowing into it. The emitted code then keys
        (suffix+head) outputs on V's values in a cross-review,
        cross-constraint memo — the audit fan-out materializes each
        distinct witness once. Returns (cut_index, V_sorted) or None."""
        body = list(body_lits)
        if not body:
            return None
        bound: set = set()
        scans = []
        for lit in body:
            sc = self._scan_lit(lit, bound)
            scans.append(sc)
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                bound -= set(e.names)
            else:
                bound |= sc["binds"]
        head_sc = self._scan_lit(
            A.Literal(expr=head_key, negated=False, withs=()), bound)
        if not head_sc["ok"] or head_sc["enum"]:
            return None
        cut = len(body)
        while cut > 0 and scans[cut - 1]["ok"] and not scans[cut - 1]["enum"]:
            cut -= 1
        if cut >= len(body):
            return None  # no usable suffix
        suffix_binds: set = set()
        reads: set = set(head_sc["reads"])
        for sc in scans[cut:]:
            reads |= sc["reads"]
            suffix_binds |= sc["binds"]
        v = sorted(reads - suffix_binds)
        if len(v) > 6:
            return None  # wide key: unlikely to collapse, skip
        return cut, v

    # --------------------------------------------------------------- rules

    def _emit_rule(self, name: str) -> None:
        rules = self.rules[name]
        kind = rules[0].kind
        if kind == "function":
            self._emit_function(name, rules)
            return
        self.em.w(0, f"def rule_{name}(_J):")
        self.em.w(1, "_m = _J['memo']")
        self.em.w(1, f"if {name!r} in _m: return _m[{name!r}]")
        self._emit_path_cache(rules, 1)
        if kind == "complete":
            self.em.w(1, "_outs = []")
            default_expr = "UNDEF"
            for r in rules:
                scope = _Scope()
                if r.is_default:
                    default_expr = self.value(
                        r.value if r.value is not None else A.Scalar(True),
                        scope, 1)
                    continue
                val_t = r.value if r.value is not None else A.Scalar(True)

                def acc(i, v):
                    self.em.w(i, f"if not any(rego_eq({v}, _o) "
                                 f"for _o in _outs): _outs.append({v})")
                self.solve(self._schedule_body(r.body), 0, scope, 1,
                           lambda i, _v=val_t, _s=scope: self.iter_emit(
                               _v, _s, i, acc))
            self.em.w(1, "if len(_outs) > 1: raise RegoError("
                         f"'complete rule {name}: multiple outputs')")
            self.em.w(1, f"_r = _outs[0] if _outs else {default_expr}")
        elif kind == "partial_set":
            self.em.w(1, "_acc = set()")
            for r in rules:
                scope = _Scope()
                body = self._schedule_body(r.body)
                plan = self._head_memo_plan(body, r.key)
                if plan is None:
                    self.solve(body, 0, scope, 1,
                               lambda i, _k=r.key, _s=scope: self.iter_emit(
                                   _k, _s, i,
                                   lambda j, v: self.em.w(j,
                                                          f"_acc.add({v})")))
                    continue
                cut, v_names = plan
                slot = self._hmemo_n
                self._hmemo_n += 1

                def suffix(i, _r=r, _s=scope, _cut=cut, _b=body):
                    self.solve(list(_b[_cut:]), 0, _s, i,
                               lambda j: self.iter_emit(
                                   _r.key, _s, j,
                                   lambda l, v: self.em.w(
                                       l, f"_hacc.append({v})")))

                def mid(i, _r=r, _s=scope, _cut=cut, _V=v_names, _sl=slot,
                        _suffix=suffix, _b=body):
                    pys = [_s.names.get(v) for v in _V]
                    if any(p is None for p in pys):
                        # planner/emitter scope mismatch: emit unmemoized
                        self.solve(list(_b[_cut:]), 0, _s, i,
                                   lambda j: self.iter_emit(
                                       _r.key, _s, j,
                                       lambda l, v: self.em.w(
                                           l, f"_acc.add({v})")))
                        return
                    hk = self.em.tmp()
                    hv = self.em.tmp()
                    key = ", ".join([str(_sl)] + pys)
                    self.em.w(i, f"{hk} = ({key},)")
                    self.em.w(i, f"{hv} = _J['hmemo'].get({hk}, _MISS)")
                    self.em.w(i, f"if {hv} is _MISS:")
                    self.em.w(i + 1, "_hacc = []")
                    _suffix(i + 1)
                    self.em.w(i + 1, f"{hv} = tuple(_hacc)")
                    self.em.w(i + 1, f"_J['hmemo'][{hk}] = {hv}")
                    fx = self.em.tmp()
                    self.em.w(i, f"for {fx} in {hv}: _acc.add({fx})")

                self.solve(list(body[:cut]), 0, scope, 1, mid)
            self.em.w(1, "_r = frozenset(_acc)")
        elif kind == "partial_object":
            self.em.w(1, "_accd = {}")
            for r in rules:
                scope = _Scope()

                def put(i, _r=r, _s=None):
                    s = _s

                    def kcont(j, kv):
                        def vcont(l, vv):
                            self.em.w(l, f"if {kv} in _accd and not "
                                         f"rego_eq(_accd[{kv}], {vv}):")
                            self.em.w(l + 1, "raise RegoError("
                                      f"'object rule {name}: conflict')")
                            self.em.w(l, f"_accd[{kv}] = {vv}")
                        self.iter_emit(_r.value, s, j, vcont)
                    self.iter_emit(_r.key, s, i, kcont)
                self.solve(self._schedule_body(r.body), 0, scope, 1,
                           lambda i, _r=r, _s=scope: put(i, _r, _s))
            self.em.w(1, "_r = FrozenDict(_accd)")
        else:
            raise Unsupported(f"rule kind {kind}")
        self.em.w(1, f"_m[{name!r}] = _r")
        self.em.w(1, "return _r")
        self.em.w(0, "")
        self._path_cache = None

    def _emit_function(self, name: str, rules) -> None:
        arity = len(rules[0].args)
        formals = [f"_a{i}" for i in range(arity)]
        self.em.w(0, f"def fn_{name}(_J, {', '.join(formals)}):")
        argnames: set = set()
        for r in rules:
            for a in r.args:
                _collect_arg_vars(a, argnames)
        if "input" in argnames:
            self._path_cache = None  # shadowed: skip hoisting
        else:
            self._emit_path_cache(rules, 1)
        memo = name in self.arg_pure
        if memo:
            self.em.w(1, f"_mk = ({name!r}, {', '.join(formals)})")
            self.em.w(1, "try:")
            self.em.w(2, "_mv = _J['fmemo'].get(_mk, _MISS)")
            self.em.w(1, "except TypeError:")  # unhashable arg: skip memo
            self.em.w(2, "_mk = None")
            self.em.w(2, "_mv = _MISS")
            self.em.w(1, "if _mv is not _MISS: return _mv")
        self.em.w(1, "_outs = []")
        for r in rules:
            if len(r.args) != arity:
                raise Unsupported(f"function {name}: mixed arity")
            scope = _Scope()
            val_t = r.value if r.value is not None else A.Scalar(True)

            def acc(i, v):
                self.em.w(i, f"if not any(rego_eq({v}, _o) "
                             f"for _o in _outs): _outs.append({v})")

            argv: set = set()
            for a in r.args:
                _collect_arg_vars(a, argv)

            def body(i, _r=r, _s=scope, _v=val_t, _argv=argv):
                self.solve(self._schedule_body(_r.body, _argv), 0, _s, i,
                           lambda j: self.iter_emit(_v, _s, j, acc))

            def chain(i, idx, _r=r, _s=scope, _body=body):
                if idx == arity:
                    _body(i)
                    return
                self.pattern(_r.args[idx], formals[idx], _s, i,
                             lambda j: chain(j, idx + 1, _r, _s, _body))
            chain(1, 0)
        self.em.w(1, f"if len(_outs) > 1: raise RegoError("
                     f"'function {name}: conflicting outputs')")
        if memo:
            self.em.w(1, "_mv = _outs[0] if _outs else UNDEF")
            self.em.w(1, "if _mk is not None: _J['fmemo'][_mk] = _mv")
            self.em.w(1, "return _mv")
        else:
            self.em.w(1, "return _outs[0] if _outs else UNDEF")
        self.em.w(0, "")
        self._path_cache = None

    # ----------------------------------------------------------- top level

    def compile(self, entry: str = "violation") -> Callable[[Any, Any], Any]:
        if entry not in self.rules:
            raise Unsupported(f"no {entry} rule")
        for name in self.rules:
            self._emit_rule(name)
        for name in sorted(self.const_rules):
            # fold: evaluate once at module build, rebind to a closure
            self.em.w(0, f"_const_{name} = rule_{name}({{'memo': {{}}}})")
            self.em.w(0, f"def rule_{name}(_J, _v=_const_{name}):")
            self.em.w(1, "return _v")
            self.em.w(0, "")
        if self._sections:
            # sections mode: review/parameters come in as direct args —
            # callers skip the per-call input-wrapper construction
            self.em.w(0, "def __evaluate__(_rev, _par, _inv, _rmemo=None, "
                         "_fmemo=None, _pmemo=None, _hmemo=None):")
            self.em.w(1, "_J = {'rev': _rev, 'par': _par, 'inv': _inv, "
                         "'memo': {}, "
                         "'rmemo': _rmemo if _rmemo is not None else {}, "
                         "'fmemo': _fmemo if _fmemo is not None else {}, "
                         "'pmemo': _pmemo if _pmemo is not None else {}, "
                         "'hmemo': _hmemo if _hmemo is not None else {}}")
        else:
            self.em.w(0, "def __evaluate__(_input, _inv, _rmemo=None, "
                         "_fmemo=None, _pmemo=None, _hmemo=None):")
            self.em.w(1, "_J = {'input': _input, 'inv': _inv, 'memo': {}, "
                         "'rmemo': _rmemo if _rmemo is not None else {}, "
                         "'fmemo': _fmemo if _fmemo is not None else {}, "
                         "'pmemo': _pmemo if _pmemo is not None else {}, "
                         "'hmemo': _hmemo if _hmemo is not None else {}}")
        if self.rules[entry][0].kind == "function":
            raise Unsupported(f"{entry} is a function")
        self.em.w(1, f"return rule_{entry}(_J)")

        params = ["UNDEF", "FrozenDict", "RegoError", "rego_eq", "_enum",
                  "_stepv", "_lookupk", "_call", "_callu", "_bin", "_neg",
                  "_arr", "_setl", "_obj", "_MISS"]
        bparams = list(self.builtin_bindings.values())
        cparams = list(self.bin_bindings.values())
        src = (f"def __make__({', '.join(params + bparams + cparams)}):\n"
               + "\n".join("    " + l for l in self.em.lines)
               + "\n    return __evaluate__\n")
        g: dict = {}
        exec(compile(src, f"<codegen:{'.'.join(self.module.package)}>",
                     "exec"), g)
        bvals = [BUILTINS[fn] for fn in self.builtin_bindings]
        cvals = [_BIN_SPECIAL[op] for op in self.bin_bindings]
        fn = g["__make__"](UNDEF, FrozenDict, RegoError, rego_eq, _enum,
                           _stepv, _lookupk, _call, _callu, _bin, _neg,
                           _arr, _setl, _obj, _MISS, *bvals, *cvals)
        fn.__source__ = src  # for debugging
        fn.__sections__ = self._sections
        if self._sections:
            def input_call(_input, _inv, *memos, _fn=fn):
                return _fn(_stepv(_input, "review"),
                           _stepv(_input, "parameters"), _inv, *memos)
            fn.__input_call__ = input_call
        else:
            fn.__input_call__ = fn
        return fn


def compile_module(module: A.Module,
                   entry: str = "violation") -> Callable[[Any, Any], Any]:
    """Compile a (merged, single-package) template module to a Python
    evaluator fn(input_frozen, inventory_frozen) -> frozen document of
    `entry`. Raises Unsupported when the module falls outside the
    compilable subset."""
    return ModuleCompiler(module).compile(entry)
