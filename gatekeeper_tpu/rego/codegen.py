"""Per-template Python code generation — the host materialization JIT.

The tree-walking interpreter (interp.py) spends ~4-5k function calls per
violation evaluation on generic unification/backtracking machinery. For the
audit tail — materializing exact messages for every (object, constraint)
pair the device filter fired — that generic cost dominates the end-to-end
wall clock (the reference's analogous cost center is the topdown evaluator
behind pkg/audit/manager.go:250-271).

This module partially evaluates the interpreter for one template: each rule
body becomes straight-line Python (nested loops for iteration, `if` chains
for guards), sharing the interpreter's value model (frozen values from
utils/values.py), its builtins (builtins.py — identical sprintf/number
formatting), and its undefined semantics (an UNDEF sentinel threaded
through helper calls). Outputs are therefore bit-identical to the
interpreter's wherever compilation succeeds; anything outside the subset
raises Unsupported at compile time and the caller keeps the interpreter
path (the same fallback discipline as the device compiler, ir/compile.py).

Differential coverage: tests/test_codegen.py runs every reference library
template's harvested corpus through both paths and asserts equality.
"""

from __future__ import annotations

import operator as _operator
from typing import Any, Callable, Optional

from . import ast as A
from .builtins import BUILTINS, BuiltinError
from .interp import UNDEF, RegoError, _binop
from .safety import reorder_module
from ..utils.values import FrozenDict, rego_eq, sort_key


class Unsupported(Exception):
    pass


_MISS = object()  # fmemo miss sentinel (UNDEF is a legitimate result)


# ----------------------------------------------------------- runtime helpers


def _enum(base):
    """Value-only _enumerate (interp.py:696): (key, value) children."""
    if isinstance(base, dict):  # FrozenDict included
        return base.items()
    if isinstance(base, tuple):
        return enumerate(base)
    if isinstance(base, frozenset):
        return ((m, m) for m in sorted(base, key=sort_key))
    return ()


def _stepv(base, key):
    """Value-only _step (interp.py:743) with UNDEF propagation."""
    if isinstance(base, dict):
        v = base.get(key, UNDEF)
        return v
    if isinstance(base, tuple):
        if isinstance(key, bool) or not isinstance(key, int):
            return UNDEF
        if 0 <= key < len(base):
            return base[key]
        return UNDEF
    if isinstance(base, frozenset):
        return key if key in base else UNDEF
    return UNDEF


def _call(fn, *args):
    """Builtin call: undefined args / builtin errors -> undefined
    (mirrors _iter_call's except clauses, interp.py:822-830)."""
    for a in args:
        if a is UNDEF:
            return UNDEF
    try:
        return fn(*args)
    except BuiltinError:
        return UNDEF
    except (TypeError, ValueError, KeyError, ZeroDivisionError):
        return UNDEF


def _callu(fn, J, *args):
    """User-function call with undefined-argument propagation."""
    for a in args:
        if a is UNDEF:
            return UNDEF
    return fn(J, *args)


def _bin(op, a, b):
    if a is UNDEF or b is UNDEF:
        return UNDEF
    return _binop(op, a, b)


def _bin_eq(a, b):
    if a is UNDEF or b is UNDEF:
        return UNDEF
    return rego_eq(a, b)


def _bin_neq(a, b):
    if a is UNDEF or b is UNDEF:
        return UNDEF
    return not rego_eq(a, b)


def _bin_minus(a, b):
    """Mirrors _binop("-"): numeric difference or set difference."""
    if a is UNDEF or b is UNDEF:
        return UNDEF
    if isinstance(a, (int, float)) and not isinstance(a, bool) and \
            isinstance(b, (int, float)) and not isinstance(b, bool):
        return a - b
    if isinstance(a, frozenset) and isinstance(b, frozenset):
        return a - b
    return UNDEF


def _mk_bin_cmp(py_op):
    def f(a, b, _cmp=py_op):
        if a is UNDEF or b is UNDEF:
            return UNDEF
        return _cmp(sort_key(a), sort_key(b))
    return f


# codegen-time specialization of the hottest comparison/difference ops:
# one closure call instead of the generic op-string dispatch chain
# (identical semantics to _binop; everything else falls through to _bin)
_BIN_SPECIAL = {
    "==": _bin_eq,
    "!=": _bin_neq,
    "-": _bin_minus,
    "<": _mk_bin_cmp(_operator.lt),
    "<=": _mk_bin_cmp(_operator.le),
    ">": _mk_bin_cmp(_operator.gt),
    ">=": _mk_bin_cmp(_operator.ge),
}


def _neg(a):
    if isinstance(a, (int, float)) and not isinstance(a, bool):
        return -a
    return UNDEF


def _arr(*xs):
    for x in xs:
        if x is UNDEF:
            return UNDEF
    return xs


def _setl(*xs):
    for x in xs:
        if x is UNDEF:
            return UNDEF
    return frozenset(xs)


def _obj(*kv):
    for x in kv:
        if x is UNDEF:
            return UNDEF
    return FrozenDict(zip(kv[0::2], kv[1::2]))


# ----------------------------------------------------------------- compiler


class _NotDeterministic(Exception):
    """Internal: term needs loop emission (unbound ref args)."""


class _Emit:
    def __init__(self):
        self.lines: list[str] = []
        self._n = 0

    def w(self, ind: int, s: str) -> None:
        self.lines.append("    " * ind + s)

    def tmp(self) -> str:
        self._n += 1
        return f"_t{self._n}"

    def src(self) -> str:
        return "\n".join(self.lines)


class _Scope:
    """Static var -> python-name map; mirrors the runtime env exactly
    because literals are compiled in the safety-reordered evaluation
    order the interpreter uses."""

    def __init__(self, names: Optional[dict] = None):
        self.names = dict(names or {})
        self.fresh: set[str] = set()

    def child(self) -> "_Scope":
        c = _Scope(self.names)
        c.fresh = set(self.fresh)
        return c

    def bound(self, name: str) -> bool:
        return name in self.names


def _term_vars(t, into: set) -> None:
    """All Var names + called function names appearing in a term."""
    if isinstance(t, A.Var):
        into.add(t.name)
    elif isinstance(t, A.Ref):
        _term_vars(t.base, into)
        for a in t.args:
            _term_vars(a, into)
    elif isinstance(t, A.Call):
        if len(t.fn) == 1:
            into.add(t.fn[0])
        else:
            into.add(t.fn[0])  # e.g. "data" roots mark impurity
        for a in t.args:
            _term_vars(a, into)
    elif isinstance(t, A.BinOp):
        _term_vars(t.lhs, into)
        _term_vars(t.rhs, into)
    elif isinstance(t, A.UnaryMinus):
        _term_vars(t.term, into)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _term_vars(x, into)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _term_vars(k, into)
            _term_vars(v, into)
    elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
        _term_vars(t.head, into)
        for lit in t.body:
            _term_vars(lit.expr, into)
    elif isinstance(t, A.ObjectCompr):
        _term_vars(t.key, into)
        _term_vars(t.value, into)
        for lit in t.body:
            _term_vars(lit.expr, into)
    elif isinstance(t, (A.Assign, A.Unify)):
        _term_vars(t.lhs, into)
        _term_vars(t.rhs, into)


class ModuleCompiler:
    def __init__(self, module: A.Module):
        module = reorder_module(module)
        self.module = module
        self.rules: dict[str, list[A.Rule]] = {}
        for r in module.rules:
            self.rules.setdefault(r.name, []).append(r)
        self.arg_pure = self._arg_pure_fns()
        self.em = _Emit()
        self.builtin_bindings: dict[tuple, str] = {}
        self.bin_bindings: dict[str, str] = {}
        self._pat_n = 0
        self._rmemo_n = 0  # review-pure comprehension memo slots

    def _arg_pure_fns(self) -> set:
        """Functions whose result depends ONLY on their arguments: no
        input/data references, no calls to non-arg-pure user rules.
        Their calls memoize on the (frozen, hashable) argument tuple —
        the inventory-join hot loops re-apply the same projection
        function to the same inventory objects once per review, so a
        memo turns O(reviews × inventory) evaluations into O(inventory).
        """
        fns = {name: rules for name, rules in self.rules.items()
               if rules[0].kind == "function"}
        deps: dict[str, set] = {}
        for name, rules in fns.items():
            names: set = set()
            for r in rules:
                if any(lit.withs for lit in r.body):
                    names.add("input")  # `with`: treat as impure
                for lit in r.body:
                    _term_vars(lit.expr, names)
                if r.value is not None:
                    _term_vars(r.value, names)
                for a in r.args:
                    _term_vars(a, names)
            deps[name] = names
        pure = set(fns)
        changed = True
        while changed:
            changed = False
            for name in list(pure):
                names = deps[name]
                if "input" in names or "data" in names:
                    pure.discard(name)
                    changed = True
                    continue
                for n in names:
                    if n in self.rules and n not in fns:
                        pure.discard(name)  # reads a document rule
                        changed = True
                        break
                    if n in fns and n not in pure:
                        pure.discard(name)
                        changed = True
                        break
        return pure

    # ------------------------------------------------------------- naming

    def _py(self, scope: _Scope, name: str) -> str:
        pn = "v_" + name.replace("$", "_w_")
        scope.names[name] = pn
        scope.fresh.discard(name)
        return pn

    def _builtin(self, fn: tuple) -> str:
        b = self.builtin_bindings.get(fn)
        if b is None:
            b = "_b" + str(len(self.builtin_bindings))
            self.builtin_bindings[fn] = b
        return b

    def _bin_expr(self, op: str, a: str, b: str) -> str:
        """Binary-op call, specialized for the hot ops (_BIN_SPECIAL)."""
        if op not in _BIN_SPECIAL:
            return f"_bin({op!r}, {a}, {b})"
        bound = self.bin_bindings.get(op)
        if bound is None:
            bound = "_c" + str(len(self.bin_bindings))
            self.bin_bindings[op] = bound
        return f"{bound}({a}, {b})"

    # -------------------------------------------------------- deterministic

    def value(self, t, scope: _Scope, ind: int) -> str:
        """Python expression for a single-valued term; may pre-emit
        statements (comprehensions). Raises _NotDeterministic when the
        term iterates (unbound ref brackets)."""
        if isinstance(t, A.Scalar):
            return repr(t.value)
        if isinstance(t, A.Var):
            return self._var_value(t.name, scope)
        if isinstance(t, A.Ref):
            return self._ref_value(t, scope, ind)
        if isinstance(t, A.Call):
            return self._call_value(t, scope, ind)
        if isinstance(t, A.BinOp):
            a = self.value(t.lhs, scope, ind)
            b = self.value(t.rhs, scope, ind)
            return self._bin_expr(t.op, a, b)
        if isinstance(t, A.UnaryMinus):
            return f"_neg({self.value(t.term, scope, ind)})"
        if isinstance(t, A.ArrayLit):
            items = [self.value(x, scope, ind) for x in t.items]
            return f"_arr({', '.join(items)})"
        if isinstance(t, A.SetLit):
            items = [self.value(x, scope, ind) for x in t.items]
            return f"_setl({', '.join(items)})"
        if isinstance(t, A.ObjectLit):
            kv = []
            for k, v in t.items:
                kv.append(self.value(k, scope, ind))
                kv.append(self.value(v, scope, ind))
            return f"_obj({', '.join(kv)})"
        if isinstance(t, (A.SetCompr, A.ArrayCompr, A.ObjectCompr)):
            return self._compr(t, scope, ind)
        raise Unsupported(f"term {type(t).__name__}")

    def _var_value(self, name: str, scope: _Scope) -> str:
        if scope.bound(name):
            return scope.names[name]
        if name == "input":
            return "_J['input']"
        if name == "data":
            raise Unsupported("bare data reference")
        rules = self.rules.get(name)
        if rules:
            if rules[0].kind == "function":
                raise Unsupported(f"function {name} in value position")
            return f"rule_{name}(_J)"
        if name.startswith("$wc") or name in scope.fresh:
            raise _NotDeterministic()
        raise Unsupported(f"unbound var {name} in value position")

    def _ref_value(self, t: A.Ref, scope: _Scope, ind: int) -> str:
        args = list(t.args)
        if isinstance(t.base, A.Var) and t.base.name == "data" and \
                not scope.bound("data"):
            if args and isinstance(args[0], A.Scalar) and \
                    args[0].value == "inventory":
                base = "_J['inv']"
                args = args[1:]
            else:
                raise Unsupported("data reference beyond inventory")
        else:
            base = self.value(t.base, scope, ind)
        for a in args:
            if isinstance(a, A.Var) and not scope.bound(a.name) and \
                    a.name not in ("input", "data"):
                raise _NotDeterministic()
            if self._is_static_pattern(a, scope):
                raise _NotDeterministic()
            base = f"_stepv({base}, {self.value(a, scope, ind)})"
        return base

    def _call_value(self, t: A.Call, scope: _Scope, ind: int) -> str:
        fn = tuple(t.fn)
        args = [self.value(a, scope, ind) for a in t.args]
        if len(fn) == 1 and fn[0] in self.rules:
            rules = self.rules[fn[0]]
            if rules[0].kind != "function":
                raise Unsupported(f"{fn[0]} is not a function")
            return f"_callu(fn_{fn[0]}, _J, {', '.join(args)})"
        if fn[0] == "data":
            raise Unsupported(f"data function call {fn}")
        if fn not in BUILTINS:
            raise Unsupported(f"unknown function {'.'.join(fn)}")
        b = self._builtin(fn)
        return f"_call({b}, {', '.join(args)})"

    # ------------------------------------------------- review-pure analysis

    def _review_pure(self, t, scope: _Scope) -> bool:
        """True when a comprehension's value depends ONLY on input.review:
        no outer-scope variable reads, no data/inventory refs, no user
        rule/function calls (they may read input.parameters), and every
        input reference steps through "review" first. Such comprehensions
        are identical across the many constraints one review is evaluated
        against in an audit, so their results are memoized per review."""
        outer = set(scope.names)

        def ok(x, bound: set) -> bool:
            if isinstance(x, A.Scalar):
                return True
            if isinstance(x, A.Var):
                if x.name in bound or x.name.startswith("$wc"):
                    return True
                # outer-scope binding (or a rule reference): impure
                return False
            if isinstance(x, A.Ref):
                if isinstance(x.base, A.Var) and x.base.name == "input" \
                        and "input" not in bound and "input" not in outer:
                    if not x.args or not (isinstance(x.args[0], A.Scalar)
                                          and x.args[0].value == "review"):
                        return False
                    return all(ok(a, bound) for a in x.args[1:])
                return ok(x.base, bound) and \
                    all(ok(a, bound) for a in x.args)
            if isinstance(x, A.Call):
                fn = tuple(x.fn)
                if fn not in BUILTINS:
                    return False  # user fn / data fn: may read parameters
                return all(ok(a, bound) for a in x.args)
            if isinstance(x, A.BinOp):
                return ok(x.lhs, bound) and ok(x.rhs, bound)
            if isinstance(x, A.UnaryMinus):
                return ok(x.term, bound)
            if isinstance(x, (A.ArrayLit, A.SetLit)):
                return all(ok(i, bound) for i in x.items)
            if isinstance(x, A.ObjectLit):
                return all(ok(k, bound) and ok(v, bound)
                           for k, v in x.items)
            return False  # nested comprehensions etc.: be conservative

        def collect_vars(x, into: set) -> None:
            """All vars a pattern-position term could bind."""
            if isinstance(x, A.Var):
                into.add(x.name)
            elif isinstance(x, (A.ArrayLit, A.SetLit)):
                for i in x.items:
                    collect_vars(i, into)
            elif isinstance(x, A.ObjectLit):
                for _k, v in x.items:
                    collect_vars(v, into)
            elif isinstance(x, A.Ref):
                for a in x.args:
                    collect_vars(a, into)

        bound: set = set()
        body = getattr(t, "body", ())
        # first pass: everything the body can bind (iteration vars,
        # unification targets, some-decls) counts as locally bound
        for lit in body:
            if lit.withs:
                return False
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                bound.update(e.names)
            elif isinstance(e, (A.Assign, A.Unify)):
                collect_vars(e.lhs, bound)
                collect_vars(e.rhs, bound)
            else:
                collect_vars(e, bound)
        bound -= outer  # outer bindings shadow nothing here: reads of them
        # are what makes the comprehension constraint-dependent
        for lit in body:
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                continue
            if isinstance(e, (A.Assign, A.Unify)):
                if not (ok(e.lhs, bound) and ok(e.rhs, bound)):
                    return False
            elif not ok(e, bound):
                return False
        heads = [h for h in (getattr(t, "head", None),
                             getattr(t, "key", None),
                             getattr(t, "value", None)) if h is not None]
        return all(ok(h, bound) for h in heads)

    def _compr(self, t, scope: _Scope, ind: int) -> str:
        if self._review_pure(t, scope):
            slot = self._rmemo_n
            self._rmemo_n += 1
            out = self.em.tmp()
            self.em.w(ind, f"{out} = _J['rmemo'].get({slot})")
            self.em.w(ind, f"if {out} is None:")
            out2 = self._compr_emit(t, scope, ind + 1)
            self.em.w(ind + 1, f"{out} = {out2}")
            self.em.w(ind + 1, f"_J['rmemo'][{slot}] = {out}")
            return out
        return self._compr_emit(t, scope, ind)

    def _compr_emit(self, t, scope: _Scope, ind: int) -> str:
        acc = self.em.tmp()
        sub = scope.child()
        if isinstance(t, A.ObjectCompr):
            self.em.w(ind, f"{acc} = {{}}")

            def done(i):
                def kcont(j, kname):
                    def vcont(l, vname):
                        self.em.w(l, f"if {kname} in {acc} and not rego_eq("
                                     f"{acc}[{kname}], {vname}):")
                        self.em.w(l + 1,
                                  "raise RegoError('object comprehension: "
                                  "conflicting key')")
                        self.em.w(l, f"{acc}[{kname}] = {vname}")
                    self.iter_emit(t.value, sub, j, vcont)
                self.iter_emit(t.key, sub, i, kcont)
            self.solve(t.body, 0, sub, ind, done)
            out = self.em.tmp()
            self.em.w(ind, f"{out} = FrozenDict({acc})")
            return out
        ctor = "frozenset" if isinstance(t, A.SetCompr) else "tuple"
        self.em.w(ind, f"{acc} = []" if ctor == "tuple" else f"{acc} = set()")
        add = f"{acc}.append" if ctor == "tuple" else f"{acc}.add"

        def done2(i):
            self.iter_emit(t.head, sub, i,
                           lambda j, v: self.em.w(j, f"{add}({v})"))
        self.solve(t.body, 0, sub, ind, done2)
        out = self.em.tmp()
        self.em.w(ind, f"{out} = {ctor}({acc})")
        return out

    # ---------------------------------------------------------- iteration

    def iter_emit(self, t, scope: _Scope, ind: int,
                  cont: Callable[[int, str], None]) -> None:
        """Emit code yielding each value of term t; cont(ind, pyname) emits
        the per-value continuation. Values passed to cont are never UNDEF
        (mirrors _iter_term: undefined terms yield nothing)."""
        try:
            expr = self.value(t, scope, ind)
        except _NotDeterministic:
            self._iter_structural(t, scope, ind, cont)
            return
        v = self.em.tmp()
        self.em.w(ind, f"{v} = {expr}")
        if isinstance(t, A.Scalar):
            cont(ind, v)
            return
        self.em.w(ind, f"if {v} is not UNDEF:")
        cont(ind + 1, v)

    def _iter_structural(self, t, scope: _Scope, ind: int, cont) -> None:
        if isinstance(t, A.Ref):
            self._iter_ref(t, scope, ind, cont)
            return
        if isinstance(t, A.Call):
            self._iter_args(list(t.args), [], scope, ind,
                            lambda i, names: self._finish_call(
                                t, names, scope, i, cont))
            return
        if isinstance(t, A.BinOp):
            def fin(i, names):
                v = self.em.tmp()
                self.em.w(i, f"{v} = "
                             f"{self._bin_expr(t.op, names[0], names[1])}")
                self.em.w(i, f"if {v} is not UNDEF:")
                cont(i + 1, v)
            self._iter_args([t.lhs, t.rhs], [], scope, ind, fin)
            return
        if isinstance(t, (A.ArrayLit, A.SetLit)):
            ctor = "_arr" if isinstance(t, A.ArrayLit) else "_setl"

            def fin2(i, names):
                v = self.em.tmp()
                self.em.w(i, f"{v} = {ctor}({', '.join(names)})")
                cont(i, v)
            self._iter_args(list(t.items), [], scope, ind, fin2)
            return
        if isinstance(t, A.ObjectLit):
            terms = [k for k, _ in t.items] + [v for _, v in t.items]

            def fin3(i, names):
                n = len(t.items)
                kv = []
                for j in range(n):
                    kv.append(names[j])
                    kv.append(names[n + j])
                v = self.em.tmp()
                self.em.w(i, f"{v} = _obj({', '.join(kv)})")
                cont(i, v)
            self._iter_args(terms, [], scope, ind, fin3)
            return
        raise Unsupported(f"iterating term {type(t).__name__}")

    def _finish_call(self, t: A.Call, argnames, scope, ind, cont):
        fn = tuple(t.fn)
        if len(fn) == 1 and fn[0] in self.rules:
            if self.rules[fn[0]][0].kind != "function":
                raise Unsupported(f"{fn[0]} is not a function")
            expr = f"_callu(fn_{fn[0]}, _J, {', '.join(argnames)})"
        elif fn in BUILTINS:
            expr = f"_call({self._builtin(fn)}, {', '.join(argnames)})"
        else:
            raise Unsupported(f"unknown function {'.'.join(fn)}")
        v = self.em.tmp()
        self.em.w(ind, f"{v} = {expr}")
        self.em.w(ind, f"if {v} is not UNDEF:")
        cont(ind + 1, v)

    def _iter_args(self, terms, names, scope, ind, fin) -> None:
        """Cross-product iteration of argument terms (interp _iter_product)."""
        if not terms:
            fin(ind, names)
            return
        self.iter_emit(terms[0], scope, ind,
                       lambda i, v: self._iter_args(
                           terms[1:], names + [v], scope, i, fin))

    def _iter_ref(self, t: A.Ref, scope: _Scope, ind: int, cont) -> None:
        args = list(t.args)
        if isinstance(t.base, A.Var) and t.base.name == "data" and \
                not scope.bound("data"):
            if args and isinstance(args[0], A.Scalar) and \
                    args[0].value == "inventory":
                base = self.em.tmp()
                self.em.w(ind, f"{base} = _J['inv']")
                self._walk(base, args[1:], scope, ind, cont)
                return
            raise Unsupported("data reference beyond inventory")
        self.iter_emit(t.base, scope, ind,
                       lambda i, b: self._walk(b, args, scope, i, cont))

    def _walk(self, base: str, args, scope: _Scope, ind: int, cont) -> None:
        if not args:
            cont(ind, base)
            return
        a = args[0]
        unbound_var = (isinstance(a, A.Var)
                       and not scope.bound(a.name)
                       and a.name not in ("input", "data"))
        if unbound_var:
            k = self.em.tmp()
            v = self.em.tmp()
            self.em.w(ind, f"for {k}, {v} in _enum({base}):")
            sub_ind = ind + 1
            if not a.name.startswith("$wc"):
                pn = self._py(scope, a.name)
                self.em.w(sub_ind, f"{pn} = {k}")
            self._walk(v, args[1:], scope, sub_ind, cont)
            return
        if self._is_static_pattern(a, scope):
            k = self.em.tmp()
            v = self.em.tmp()
            self.em.w(ind, f"for {k}, {v} in _enum({base}):")
            self.pattern(a, k, scope, ind + 1,
                         lambda i: self._walk(v, args[1:], scope, i, cont))
            return
        key = self.value(a, scope, ind)
        nxt = self.em.tmp()
        self.em.w(ind, f"{nxt} = _stepv({base}, {key})")
        self.em.w(ind, f"if {nxt} is not UNDEF:")
        self._walk(nxt, args[1:], scope, ind + 1, cont)

    # ------------------------------------------------------------ patterns

    def _is_static_pattern(self, t, scope: _Scope) -> bool:
        """Static mirror of interp._is_pattern over the tracked scope."""
        if isinstance(t, A.Var):
            if t.name in ("input", "data") and not scope.bound(t.name):
                return False
            return not scope.bound(t.name)
        if isinstance(t, A.ArrayLit):
            return any(self._is_static_pattern(x, scope) for x in t.items)
        if isinstance(t, A.ObjectLit):
            return any(self._is_static_pattern(v, scope)
                       for _, v in t.items)
        return False

    def pattern(self, t, val: str, scope: _Scope, ind: int, cont) -> None:
        """Emit unification of pattern t against value `val`
        (mirrors _unify_pattern, interp.py:487)."""
        if isinstance(t, A.Var):
            if not scope.bound(t.name):
                if t.name.startswith("$wc"):
                    cont(ind)
                    return
                pn = self._py(scope, t.name)
                self.em.w(ind, f"{pn} = {val}")
                cont(ind)
                return
            self.em.w(ind, f"if rego_eq({scope.names[t.name]}, {val}):")
            cont(ind + 1)
            return
        if isinstance(t, A.ArrayLit):
            n = len(t.items)
            self.em.w(ind, f"if isinstance({val}, tuple) and "
                           f"len({val}) == {n}:")
            def chain(i, idx):
                if idx == n:
                    cont(i)
                    return
                el = self.em.tmp()
                self.em.w(i, f"{el} = {val}[{idx}]")
                self.pattern(t.items[idx], el, scope, i,
                             lambda j: chain(j, idx + 1))
            chain(ind + 1, 0)
            return
        if isinstance(t, A.ObjectLit):
            n = len(t.items)
            self.em.w(ind, f"if isinstance({val}, FrozenDict) and "
                           f"len({val}) == {n}:")
            items = list(t.items)

            def ochain(i, idx):
                if idx == n:
                    cont(i)
                    return
                k_t, v_t = items[idx]
                kx = self.value(k_t, scope, i)
                kv = self.em.tmp()
                self.em.w(i, f"{kv} = {kx}")
                self.em.w(i, f"if {kv} in {val}:")
                el = self.em.tmp()
                self.em.w(i + 1, f"{el} = {val}[{kv}]")
                self.pattern(v_t, el, scope, i + 1,
                             lambda j: ochain(j, idx + 1))
            ochain(ind + 1, 0)
            return
        # ground term: compare (final case of _unify_pattern)
        expr = self.value(t, scope, ind)
        self.em.w(ind, f"if rego_eq({expr}, {val}):")
        cont(ind + 1)

    # ------------------------------------------------------------- literals

    def solve(self, lits, i: int, scope: _Scope, ind: int, done) -> None:
        """Emit body literals [i:], then done(ind) at full success."""
        if i == len(lits):
            done(ind)
            return
        lit = lits[i]
        nxt = lambda j: self.solve(lits, i + 1, scope, j, done)
        if lit.withs:
            raise Unsupported("with modifier")
        expr = lit.expr
        if lit.negated:
            self._emit_negation(expr, scope, ind, nxt)
            return
        if isinstance(expr, A.SomeDecl):
            for n in expr.names:
                scope.fresh.add(n)
                scope.names.pop(n, None)
            nxt(ind)
            return
        if isinstance(expr, (A.Assign, A.Unify)):
            self._emit_unify(expr, scope, ind, nxt)
            return
        # plain expression literal: succeeds per non-false value
        self.iter_emit(expr, scope, ind, lambda j, v: (
            self.em.w(j, f"if {v} is not False:"), nxt(j + 1)))

    def _emit_negation(self, expr, scope: _Scope, ind: int, nxt) -> None:
        fn = self.em.tmp()
        self.em.w(ind, f"def _ng{fn}():")
        sub = scope.child()
        body_ind = ind + 1
        wrote = len(self.em.lines)
        if isinstance(expr, (A.Assign, A.Unify)):
            # expression position: unify success -> exists
            self._emit_unify(expr, sub, body_ind,
                             lambda j: self.em.w(j, "return True"))
        else:
            self.iter_emit(expr, sub, body_ind, lambda j, v: (
                self.em.w(j, f"if {v} is not False:"),
                self.em.w(j + 1, "return True")))
        if len(self.em.lines) == wrote:
            self.em.w(body_ind, "pass")
        self.em.w(body_ind, "return False")
        self.em.w(ind, f"if not _ng{fn}():")
        nxt(ind + 1)

    def _emit_unify(self, expr, scope: _Scope, ind: int, nxt) -> None:
        assign = isinstance(expr, A.Assign)
        lhs, rhs = expr.lhs, expr.rhs
        lp = assign or self._is_static_pattern(lhs, scope)
        rp = (not assign) and self._is_static_pattern(rhs, scope)
        if lp and rp:
            raise Unsupported("unifying two non-ground terms")
        if lp:
            self.iter_emit(rhs, scope, ind, lambda i, v:
                           self.pattern(lhs, v, scope, i, nxt))
            return
        if rp:
            self.iter_emit(lhs, scope, ind, lambda i, v:
                           self.pattern(rhs, v, scope, i, nxt))
            return
        def both(i, a):
            self.iter_emit(rhs, scope, i, lambda j, b: (
                self.em.w(j, f"if rego_eq({a}, {b}):"), nxt(j + 1)))
        self.iter_emit(lhs, scope, ind, both)

    # --------------------------------------------------------------- rules

    def _emit_rule(self, name: str) -> None:
        rules = self.rules[name]
        kind = rules[0].kind
        if kind == "function":
            self._emit_function(name, rules)
            return
        self.em.w(0, f"def rule_{name}(_J):")
        self.em.w(1, "_m = _J['memo']")
        self.em.w(1, f"if {name!r} in _m: return _m[{name!r}]")
        if kind == "complete":
            self.em.w(1, "_outs = []")
            default_expr = "UNDEF"
            for r in rules:
                scope = _Scope()
                if r.is_default:
                    default_expr = self.value(
                        r.value if r.value is not None else A.Scalar(True),
                        scope, 1)
                    continue
                val_t = r.value if r.value is not None else A.Scalar(True)

                def acc(i, v):
                    self.em.w(i, f"if not any(rego_eq({v}, _o) "
                                 f"for _o in _outs): _outs.append({v})")
                self.solve(r.body, 0, scope, 1,
                           lambda i, _v=val_t, _s=scope: self.iter_emit(
                               _v, _s, i, acc))
            self.em.w(1, "if len(_outs) > 1: raise RegoError("
                         f"'complete rule {name}: multiple outputs')")
            self.em.w(1, f"_r = _outs[0] if _outs else {default_expr}")
        elif kind == "partial_set":
            self.em.w(1, "_acc = set()")
            for r in rules:
                scope = _Scope()
                self.solve(r.body, 0, scope, 1,
                           lambda i, _k=r.key, _s=scope: self.iter_emit(
                               _k, _s, i,
                               lambda j, v: self.em.w(j, f"_acc.add({v})")))
            self.em.w(1, "_r = frozenset(_acc)")
        elif kind == "partial_object":
            self.em.w(1, "_accd = {}")
            for r in rules:
                scope = _Scope()

                def put(i, _r=r, _s=None):
                    s = _s

                    def kcont(j, kv):
                        def vcont(l, vv):
                            self.em.w(l, f"if {kv} in _accd and not "
                                         f"rego_eq(_accd[{kv}], {vv}):")
                            self.em.w(l + 1, "raise RegoError("
                                      f"'object rule {name}: conflict')")
                            self.em.w(l, f"_accd[{kv}] = {vv}")
                        self.iter_emit(_r.value, s, j, vcont)
                    self.iter_emit(_r.key, s, i, kcont)
                self.solve(r.body, 0, scope, 1,
                           lambda i, _r=r, _s=scope: put(i, _r, _s))
            self.em.w(1, "_r = FrozenDict(_accd)")
        else:
            raise Unsupported(f"rule kind {kind}")
        self.em.w(1, f"_m[{name!r}] = _r")
        self.em.w(1, "return _r")
        self.em.w(0, "")

    def _emit_function(self, name: str, rules) -> None:
        arity = len(rules[0].args)
        formals = [f"_a{i}" for i in range(arity)]
        self.em.w(0, f"def fn_{name}(_J, {', '.join(formals)}):")
        memo = name in self.arg_pure
        if memo:
            self.em.w(1, f"_mk = ({name!r}, {', '.join(formals)})")
            self.em.w(1, "try:")
            self.em.w(2, "_mv = _J['fmemo'].get(_mk, _MISS)")
            self.em.w(1, "except TypeError:")  # unhashable arg: skip memo
            self.em.w(2, "_mk = None")
            self.em.w(2, "_mv = _MISS")
            self.em.w(1, "if _mv is not _MISS: return _mv")
        self.em.w(1, "_outs = []")
        for r in rules:
            if len(r.args) != arity:
                raise Unsupported(f"function {name}: mixed arity")
            scope = _Scope()
            val_t = r.value if r.value is not None else A.Scalar(True)

            def acc(i, v):
                self.em.w(i, f"if not any(rego_eq({v}, _o) "
                             f"for _o in _outs): _outs.append({v})")

            def body(i, _r=r, _s=scope, _v=val_t):
                self.solve(_r.body, 0, _s, i,
                           lambda j: self.iter_emit(_v, _s, j, acc))

            def chain(i, idx, _r=r, _s=scope, _body=body):
                if idx == arity:
                    _body(i)
                    return
                self.pattern(_r.args[idx], formals[idx], _s, i,
                             lambda j: chain(j, idx + 1, _r, _s, _body))
            chain(1, 0)
        self.em.w(1, f"if len(_outs) > 1: raise RegoError("
                     f"'function {name}: conflicting outputs')")
        if memo:
            self.em.w(1, "_mv = _outs[0] if _outs else UNDEF")
            self.em.w(1, "if _mk is not None: _J['fmemo'][_mk] = _mv")
            self.em.w(1, "return _mv")
        else:
            self.em.w(1, "return _outs[0] if _outs else UNDEF")
        self.em.w(0, "")

    # ----------------------------------------------------------- top level

    def compile(self, entry: str = "violation") -> Callable[[Any, Any], Any]:
        if entry not in self.rules:
            raise Unsupported(f"no {entry} rule")
        for name in self.rules:
            self._emit_rule(name)
        self.em.w(0, "def __evaluate__(_input, _inv, _rmemo=None, "
                     "_fmemo=None):")
        self.em.w(1, "_J = {'input': _input, 'inv': _inv, 'memo': {}, "
                     "'rmemo': _rmemo if _rmemo is not None else {}, "
                     "'fmemo': _fmemo if _fmemo is not None else {}}")
        if self.rules[entry][0].kind == "function":
            raise Unsupported(f"{entry} is a function")
        self.em.w(1, f"return rule_{entry}(_J)")

        params = ["UNDEF", "FrozenDict", "RegoError", "rego_eq", "_enum",
                  "_stepv", "_call", "_callu", "_bin", "_neg", "_arr",
                  "_setl", "_obj", "_MISS"]
        bparams = list(self.builtin_bindings.values())
        cparams = list(self.bin_bindings.values())
        src = (f"def __make__({', '.join(params + bparams + cparams)}):\n"
               + "\n".join("    " + l for l in self.em.lines)
               + "\n    return __evaluate__\n")
        g: dict = {}
        exec(compile(src, f"<codegen:{'.'.join(self.module.package)}>",
                     "exec"), g)
        bvals = [BUILTINS[fn] for fn in self.builtin_bindings]
        cvals = [_BIN_SPECIAL[op] for op in self.bin_bindings]
        fn = g["__make__"](UNDEF, FrozenDict, RegoError, rego_eq, _enum,
                           _stepv, _call, _callu, _bin, _neg, _arr, _setl,
                           _obj, _MISS, *bvals, *cvals)
        fn.__source__ = src  # for debugging
        return fn


def compile_module(module: A.Module,
                   entry: str = "violation") -> Callable[[Any, Any], Any]:
    """Compile a (merged, single-package) template module to a Python
    evaluator fn(input_frozen, inventory_frozen) -> frozen document of
    `entry`. Raises Unsupported when the module falls outside the
    compilable subset."""
    return ModuleCompiler(module).compile(entry)
