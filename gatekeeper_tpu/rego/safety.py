"""Body-literal reordering (safety analysis).

OPA's compiler reorders rule-body literals so every variable is bound before
it is *needed* (ast/compile.go "reorderBodyForSafety"). Source order is not
evaluation order — e.g. library/general/uniqueserviceselector/src.rego:

    selectors := [s | s = concat(":", [key, val]); val = obj.spec.selector[key]]

where the first comprehension literal consumes key/val that only the second
binds. This pass replicates that: a greedy topological sort where a literal
is schedulable once all its needed vars are bound, applied recursively to
comprehension bodies.

Positions that can BIND a var: lhs/rhs pattern positions of `=`/`:=`
(nested array/object-value patterns included) and ref bracket arguments.
Positions that NEED a var bound: builtin/function call arguments, ref bases,
binop operands, object keys, everything under negation. Comprehensions bind
their own locals; only their residual free vars are needed from the outer
scope.
"""

from __future__ import annotations

from . import ast as A

_GLOBALS = ("input", "data")


def _is_binding_pattern(t) -> bool:
    if isinstance(t, A.Var):
        return True
    if isinstance(t, A.ArrayLit):
        return all(_is_binding_pattern(x) or isinstance(x, A.Scalar) for x in t.items)
    if isinstance(t, A.ObjectLit):
        return all(
            _is_binding_pattern(v) or isinstance(v, A.Scalar) for _, v in t.items
        )
    return False


def _pattern_vars(t, out: set):
    if isinstance(t, A.Var):
        out.add(t.name)
    elif isinstance(t, A.ArrayLit):
        for x in t.items:
            _pattern_vars(x, out)
    elif isinstance(t, A.ObjectLit):
        for _, v in t.items:
            _pattern_vars(v, out)


def _term_vars(t, needed: set, bound: set):
    """Collect vars of term t into `needed` (must be pre-bound) and `bound`
    (bindable by evaluating this term in a positive literal)."""
    if isinstance(t, A.Var):
        needed.add(t.name)
    elif isinstance(t, A.Ref):
        if isinstance(t.base, A.Var):
            needed.add(t.base.name)
        else:
            _term_vars(t.base, needed, bound)
        for a in t.args:
            if isinstance(a, A.Var):
                bound.add(a.name)  # unbound bracket vars enumerate
            elif _is_binding_pattern(a):
                _pattern_vars(a, bound)
            else:
                _term_vars(a, needed, bound)
    elif isinstance(t, A.Call):
        for a in t.args:
            _term_vars(a, needed, bound)
    elif isinstance(t, A.BinOp):
        _term_vars(t.lhs, needed, bound)
        _term_vars(t.rhs, needed, bound)
    elif isinstance(t, A.UnaryMinus):
        _term_vars(t.term, needed, bound)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _term_vars(x, needed, bound)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _term_vars(k, needed, bound)
            _term_vars(v, needed, bound)
    elif isinstance(t, A.ArrayCompr):
        needed.update(_compr_free(list(t.body), [t.head]))
    elif isinstance(t, A.SetCompr):
        needed.update(_compr_free(list(t.body), [t.head]))
    elif isinstance(t, A.ObjectCompr):
        needed.update(_compr_free(list(t.body), [t.key, t.value]))


def _compr_free(body: list, heads: list) -> set:
    """Free vars of a comprehension = (needed by body+heads) - (bindable in body)."""
    needed: set = set()
    bindable: set = set()
    for lit in body:
        n, b = _literal_vars(lit)
        needed |= n
        bindable |= b
    for h in heads:
        hn: set = set()
        hb: set = set()
        _term_vars(h, hn, hb)
        needed |= hn
    return needed - bindable


def _literal_vars(lit: A.Literal):
    """Return (needed, bindable) var sets for a literal."""
    needed: set = set()
    bindable: set = set()
    e = lit.expr
    if isinstance(e, A.SomeDecl):
        bindable.update(e.names)
    elif isinstance(e, (A.Assign, A.Unify)):
        for side in (e.lhs, e.rhs):
            if _is_binding_pattern(side):
                _pattern_vars(side, bindable)
            else:
                _term_vars(side, needed, bindable)
    else:
        _term_vars(e, needed, bindable)
    if lit.negated:
        needed |= bindable
        bindable = set()
    for w in lit.withs:
        wn: set = set()
        wb: set = set()
        _term_vars(w.value, wn, wb)
        needed |= wn | wb
    needed -= set(_GLOBALS)
    bindable = {v for v in bindable if v not in _GLOBALS}
    return needed, bindable


def reorder_body(body: tuple, rule_names: set, pre_bound: set) -> tuple:
    body = tuple(_reorder_terms(lit, rule_names) for lit in body)
    if len(body) < 2:
        return body
    pending = list(body)
    bound = set(pre_bound)
    out = []
    infos = {id(l): _literal_vars(l) for l in pending}
    # vars no literal can bind must come from the outer scope (comprehension
    # closures) or be rule references — treat them as already bound
    all_bindable: set = set()
    for _, b in infos.values():
        all_bindable |= b
    while pending:
        progressed = False
        for i, lit in enumerate(pending):
            needed, _ = infos[id(lit)]
            unmet = {
                v
                for v in needed
                if v in all_bindable
                and v not in bound
                and v not in rule_names
                and not v.startswith("$wc")
            }
            if not unmet:
                out.append(lit)
                bound |= infos[id(lit)][1]
                # a scheduled positive literal also grounds its needed vars
                bound |= needed
                del pending[i]
                progressed = True
                break
        if not progressed:
            # unsatisfiable ordering: keep source order for the remainder and
            # let evaluation report the unsafe var
            out.extend(pending)
            break
    return tuple(out)


def _reorder_terms(lit: A.Literal, rule_names: set) -> A.Literal:
    """Recursively reorder comprehension bodies inside a literal."""

    def rt(t):
        if isinstance(t, A.ArrayCompr):
            return A.ArrayCompr(rt(t.head), reorder_body(t.body, rule_names, set()))
        if isinstance(t, A.SetCompr):
            return A.SetCompr(rt(t.head), reorder_body(t.body, rule_names, set()))
        if isinstance(t, A.ObjectCompr):
            return A.ObjectCompr(
                rt(t.key), rt(t.value), reorder_body(t.body, rule_names, set())
            )
        if isinstance(t, A.Ref):
            return A.Ref(rt(t.base), tuple(rt(a) for a in t.args))
        if isinstance(t, A.Call):
            return A.Call(t.fn, tuple(rt(a) for a in t.args))
        if isinstance(t, A.BinOp):
            return A.BinOp(t.op, rt(t.lhs), rt(t.rhs))
        if isinstance(t, A.UnaryMinus):
            return A.UnaryMinus(rt(t.term))
        if isinstance(t, A.ArrayLit):
            return A.ArrayLit(tuple(rt(x) for x in t.items))
        if isinstance(t, A.SetLit):
            return A.SetLit(tuple(rt(x) for x in t.items))
        if isinstance(t, A.ObjectLit):
            return A.ObjectLit(tuple((rt(k), rt(v)) for k, v in t.items))
        if isinstance(t, (A.Assign, A.Unify)):
            cls = type(t)
            return cls(rt(t.lhs), rt(t.rhs))
        return t

    return A.Literal(
        expr=rt(lit.expr),
        negated=lit.negated,
        withs=tuple(A.WithMod(w.target, rt(w.value)) for w in lit.withs),
        line=lit.line,
    )


def reorder_module(m: A.Module) -> A.Module:
    rule_names = {r.name for r in m.rules}
    new_rules = []
    for r in m.rules:
        pre: set = set()
        for a in r.args:
            _pattern_vars(a, pre)
        new_rules.append(
            A.Rule(
                name=r.name,
                kind=r.kind,
                args=r.args,
                key=r.key,
                value=r.value,
                body=reorder_body(r.body, rule_names, pre),
                is_default=r.is_default,
                line=r.line,
            )
        )
    return A.Module(
        package=m.package,
        imports=m.imports,
        rules=tuple(new_rules),
        source_name=m.source_name,
    )
