"""Mutator CRD types: Assign / AssignMetadata / ModifySet.

Counterparts of the reference's pkg/mutation/mutators/{assign,
assignmeta,modifyset}: each wraps one mutator CR, validates its spec at
ingestion time, and knows how to apply itself to an unstructured object
in place. Applicability (applyTo + spec.match) is evaluated separately —
batched across a whole micro-batch by the MutationSystem through the
same vectorized target-matcher the validation path uses.

Semantics mirrored from the reference:

  * Assign may not mutate `metadata.*` (that is AssignMetadata's job)
    and requires a non-empty `applyTo`.
  * AssignMetadata may ONLY write `metadata.labels.<key>` /
    `metadata.annotations.<key>`, the assigned value must be a string,
    and an existing value is never overwritten.
  * ModifySet's location terminates at a list; `merge` appends missing
    values (creating the list if absent), `prune` removes equal values.
  * Traversal creates missing object fields and — for concrete-keyed
    list accessors — missing elements (seeded with the key field); glob
    accessors never create.
"""

from __future__ import annotations

import copy
import json
from typing import Any, Optional

from .path import ListNode, ObjectNode, PathError, PathNode, parse, render

MUTATOR_GROUP = "mutations.gatekeeper.sh"
MUTATOR_KINDS = ("Assign", "AssignMetadata", "ModifySet")


class MutationError(Exception):
    pass


def _spec(obj: dict) -> dict:
    spec = obj.get("spec")
    return spec if isinstance(spec, dict) else {}


class Mutator:
    """One validated mutator CR. `id` is (kind, name) — the ingestion
    cache key; `nodes` the parsed location path."""

    kind: str = ""

    def __init__(self, obj: dict):
        self.obj = copy.deepcopy(obj)
        meta = self.obj.get("metadata")
        self.name = (meta or {}).get("name") or ""
        if not self.name:
            raise MutationError(f"{self.kind} has no metadata.name")
        self.id: tuple[str, str] = (self.kind, self.name)
        spec = _spec(self.obj)
        location = spec.get("location")
        try:
            self.nodes: list[PathNode] = parse(location)
        except PathError as e:
            raise MutationError(f"{self.kind} {self.name}: bad "
                                f"spec.location: {e}") from e
        self.match = spec.get("match") or {}
        if not isinstance(self.match, dict):
            raise MutationError(f"{self.kind} {self.name}: spec.match "
                                "must be an object")
        self.apply_to = self._parse_apply_to(spec)
        self._validate(spec)

    # ---------------------------------------------------------- applyTo

    def _parse_apply_to(self, spec: dict) -> Optional[list[dict]]:
        apply_to = spec.get("applyTo")
        if apply_to is None:
            return None
        if not isinstance(apply_to, list):
            raise MutationError(f"{self.kind} {self.name}: spec.applyTo "
                                "must be an array")
        out = []
        for i, entry in enumerate(apply_to):
            if not isinstance(entry, dict):
                raise MutationError(f"{self.kind} {self.name}: "
                                    f"spec.applyTo[{i}] must be an object")
            out.append({
                "groups": [g for g in entry.get("groups") or []
                           if isinstance(g, str)],
                "versions": [v for v in entry.get("versions") or []
                             if isinstance(v, str)],
                "kinds": [k for k in entry.get("kinds") or []
                          if isinstance(k, str)],
            })
        return out

    def applies_to_gvk(self, group: str, version: str, kind: str) -> bool:
        """applyTo gate (reference match.AppliesTo): any entry whose
        three lists each contain the value or `*`. A mutator without
        applyTo (AssignMetadata) applies to every kind."""
        if self.apply_to is None:
            return True
        for entry in self.apply_to:
            if (("*" in entry["groups"] or group in entry["groups"])
                    and ("*" in entry["versions"]
                         or version in entry["versions"])
                    and ("*" in entry["kinds"] or kind in entry["kinds"])):
                return True
        return False

    # ------------------------------------------------------- validation

    def _validate(self, spec: dict) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------ apply

    def apply(self, obj: dict) -> bool:
        """Mutate `obj` in place; True iff anything changed."""
        raise NotImplementedError

    def location(self) -> str:
        return render(self.nodes)

    def __repr__(self):
        return f"<{self.kind} {self.name} @ {self.location()}>"


# --------------------------------------------------------- path traversal


def _descend(parent: dict, node: PathNode, create: bool,
             who: str) -> list[Any]:
    """Resolve one non-terminal path node to the child containers to
    recurse into (possibly creating them). Returns [] when the path
    does not resolve and must not be created."""
    if isinstance(node, ObjectNode):
        child = parent.get(node.name)
        if child is None:
            if not create:
                return []
            child = parent[node.name] = {}
        if not isinstance(child, dict):
            raise MutationError(
                f"{who}: {node.name} is not an object (found "
                f"{type(child).__name__})")
        return [child]
    lst = parent.get(node.name)
    if lst is None:
        if not create or node.glob:
            return []
        lst = parent[node.name] = []
    if not isinstance(lst, list):
        raise MutationError(f"{who}: {node.name} is not a list (found "
                            f"{type(lst).__name__})")
    matched = [el for el in lst
               if isinstance(el, dict)
               and (node.glob or el.get(node.key_field) == node.key_value)]
    if not matched and not node.glob:
        if not create:
            return []
        el: dict = {node.key_field: node.key_value}
        lst.append(el)
        matched = [el]
    return matched


# ------------------------------------------------------------------ Assign


class AssignMutator(Mutator):
    kind = "Assign"

    def _validate(self, spec: dict) -> None:
        if not self.apply_to:
            raise MutationError(f"Assign {self.name}: spec.applyTo is "
                                "required and must be non-empty")
        first = self.nodes[0]
        if first.name == "metadata":
            raise MutationError(f"Assign {self.name}: changing metadata is "
                                "not allowed (use AssignMetadata)")
        params = spec.get("parameters")
        params = params if isinstance(params, dict) else {}
        assign = params.get("assign")
        if not isinstance(assign, dict) or "value" not in assign:
            raise MutationError(f"Assign {self.name}: "
                                "spec.parameters.assign.value is required")
        self.value = assign["value"]
        last = self.nodes[-1]
        if isinstance(last, ListNode):
            if last.glob:
                # a glob terminal would rewrite every element with one
                # identical value, dropping the key field that
                # distinguishes them (the reference forbids it too)
                raise MutationError(
                    f"Assign {self.name}: the final list node may not "
                    "use the glob key (it would collapse every element "
                    "into one value)")
            if not (isinstance(self.value, dict)
                    and self.value.get(last.key_field) == last.key_value):
                raise MutationError(
                    f"Assign {self.name}: value for terminal "
                    f"[{last.key_field}: {last.key_value}] must be an "
                    "object carrying that key")

    def apply(self, obj: dict) -> bool:
        who = f"Assign {self.name}"
        parents = [obj]
        for node in self.nodes[:-1]:
            nxt: list = []
            for p in parents:
                nxt.extend(_descend(p, node, create=True, who=who))
            parents = nxt
        changed = False
        last = self.nodes[-1]
        for p in parents:
            if isinstance(last, ObjectNode):
                if p.get(last.name) != self.value or last.name not in p:
                    p[last.name] = copy.deepcopy(self.value)
                    changed = True
                continue
            lst = p.get(last.name)
            if lst is None:
                lst = p[last.name] = []
            if not isinstance(lst, list):
                raise MutationError(f"{who}: {last.name} is not a list")
            # glob terminals are rejected at validation; only concrete
            # keys reach here
            hit = False
            for i, el in enumerate(lst):
                if isinstance(el, dict) and \
                        el.get(last.key_field) == last.key_value:
                    hit = True
                    if el != self.value:
                        lst[i] = copy.deepcopy(self.value)
                        changed = True
            if not hit:
                lst.append(copy.deepcopy(self.value))
                changed = True
        return changed


# ---------------------------------------------------------- AssignMetadata


class AssignMetadataMutator(Mutator):
    kind = "AssignMetadata"

    def _validate(self, spec: dict) -> None:
        nodes = self.nodes
        ok = (len(nodes) == 3
              and all(isinstance(n, ObjectNode) for n in nodes)
              and nodes[0].name == "metadata"
              and nodes[1].name in ("labels", "annotations"))
        if not ok:
            raise MutationError(
                f"AssignMetadata {self.name}: location must be "
                "metadata.labels.<key> or metadata.annotations.<key>, "
                f"got {spec.get('location')!r}")
        params = spec.get("parameters")
        params = params if isinstance(params, dict) else {}
        assign = params.get("assign")
        if not isinstance(assign, dict) or "value" not in assign:
            raise MutationError(f"AssignMetadata {self.name}: "
                                "spec.parameters.assign.value is required")
        if not isinstance(assign["value"], str):
            raise MutationError(f"AssignMetadata {self.name}: value must "
                                "be a string")
        self.value = assign["value"]

    def apply(self, obj: dict) -> bool:
        meta = obj.setdefault("metadata", {})
        if not isinstance(meta, dict):
            raise MutationError(f"AssignMetadata {self.name}: metadata is "
                                "not an object")
        bucket = meta.setdefault(self.nodes[1].name, {})
        if not isinstance(bucket, dict):
            raise MutationError(
                f"AssignMetadata {self.name}: metadata."
                f"{self.nodes[1].name} is not an object")
        key = self.nodes[2].name
        if key in bucket:
            return False  # never overwrites (reference assignmeta.go)
        bucket[key] = self.value
        return True


# --------------------------------------------------------------- ModifySet


class ModifySetMutator(Mutator):
    kind = "ModifySet"

    def _validate(self, spec: dict) -> None:
        if not self.apply_to:
            raise MutationError(f"ModifySet {self.name}: spec.applyTo is "
                                "required and must be non-empty")
        first = self.nodes[0]
        if first.name == "metadata":
            raise MutationError(f"ModifySet {self.name}: changing metadata "
                                "is not allowed")
        if isinstance(self.nodes[-1], ListNode):
            raise MutationError(
                f"ModifySet {self.name}: location must terminate at the "
                "list field itself, not a keyed element")
        params = spec.get("parameters")
        params = params if isinstance(params, dict) else {}
        self.operation = params.get("operation") or "merge"
        if self.operation not in ("merge", "prune"):
            raise MutationError(f"ModifySet {self.name}: operation must be "
                                "merge or prune")
        values = params.get("values")
        values = values if isinstance(values, dict) else {}
        from_list = values.get("fromList")
        if not isinstance(from_list, list):
            raise MutationError(f"ModifySet {self.name}: "
                                "spec.parameters.values.fromList is required")
        self.values = from_list

    def apply(self, obj: dict) -> bool:
        who = f"ModifySet {self.name}"
        # prune must not create the path it would prune from
        create = self.operation == "merge"
        parents = [obj]
        for node in self.nodes[:-1]:
            nxt: list = []
            for p in parents:
                nxt.extend(_descend(p, node, create=create, who=who))
            parents = nxt
        last = self.nodes[-1]
        changed = False
        for p in parents:
            lst = p.get(last.name)
            if lst is None:
                if not create:
                    continue
                lst = p[last.name] = []
            if not isinstance(lst, list):
                raise MutationError(f"{who}: {last.name} is not a list")
            if self.operation == "merge":
                for v in self.values:
                    if v not in lst:
                        lst.append(copy.deepcopy(v))
                        changed = True
            else:
                kept = [el for el in lst if el not in self.values]
                if len(kept) != len(lst):
                    lst[:] = kept
                    changed = True
        return changed


_BY_KIND = {
    "Assign": AssignMutator,
    "AssignMetadata": AssignMetadataMutator,
    "ModifySet": ModifySetMutator,
}


def load_mutator(obj: Any) -> Mutator:
    """Validate + wrap a mutator CR dict; raises MutationError."""
    if not isinstance(obj, dict):
        raise MutationError(f"mutator must be an object, got "
                            f"{type(obj).__name__}")
    kind = obj.get("kind")
    cls = _BY_KIND.get(kind)
    if cls is None:
        raise MutationError(f"unknown mutator kind {kind!r}; expected one "
                            f"of {MUTATOR_KINDS}")
    group = (obj.get("apiVersion") or "").partition("/")[0]
    if group and group != MUTATOR_GROUP:
        raise MutationError(f"mutator group must be {MUTATOR_GROUP}, got "
                            f"{group!r}")
    return cls(obj)


def semantic_equal(a: dict, b: dict) -> bool:
    """Spec-level equality for ingestion dedupe (metadata churn —
    resourceVersion, managedFields — must not re-ingest)."""
    return json.dumps(_spec(a), sort_keys=True) == \
        json.dumps(_spec(b), sort_keys=True)
