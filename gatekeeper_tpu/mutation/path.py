"""Location-path parser for mutator `spec.location`.

Counterpart of the reference's mutation path parser
(pkg/mutation/path/parser): a dotted path whose segments are object
fields or keyed list accessors, e.g.

    spec.containers[name: *].imagePullPolicy
    spec.template.spec.tolerations
    metadata.labels."corp.example/team"

Grammar:

    path     := segment ("." segment)*
    segment  := field listSpec?
    field    := IDENT | STRING
    listSpec := "[" field ":" (field | "*") "]"
    IDENT    := [A-Za-z0-9_-]+
    STRING   := double-quoted, backslash escapes for `"` and `\\`

A keyed list accessor names the list-typed field, the key field its
elements are keyed by, and either a concrete key value or the glob `*`
(match every element; globs never create elements). Paths render back
canonically via `render()` and round-trip through `parse()`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Union


class PathError(Exception):
    pass


@dataclass(frozen=True)
class ObjectNode:
    """`.field` — descend into (or terminally name) an object field."""
    name: str


@dataclass(frozen=True)
class ListNode:
    """`.field[key: value]` — `field` holds a list of objects keyed by
    `key`; `glob` selects every element (value was `*`)."""
    name: str
    key_field: str
    key_value: Union[str, int, None]
    glob: bool = False


PathNode = Union[ObjectNode, ListNode]

_IDENT_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-")


def _tokenize(path: str) -> list[tuple[str, str]]:
    """[(type, text)] with types IDENT, STRING, GLOB, and the literal
    punctuation '.', '[', ']', ':'."""
    toks: list[tuple[str, str]] = []
    i, n = 0, len(path)
    while i < n:
        ch = path[i]
        if ch in ".[]:":
            toks.append((ch, ch))
            i += 1
        elif ch == "*":
            toks.append(("GLOB", "*"))
            i += 1
        elif ch == '"':
            j = i + 1
            out = []
            while j < n and path[j] != '"':
                if path[j] == "\\":
                    j += 1
                    if j >= n or path[j] not in ('"', "\\"):
                        raise PathError(
                            f"invalid escape in quoted field at {j}: {path!r}")
                out.append(path[j])
                j += 1
            if j >= n:
                raise PathError(f"unterminated quoted field: {path!r}")
            toks.append(("STRING", "".join(out)))
            i = j + 1
        elif ch.isspace():
            i += 1  # whitespace is insignificant (reference allows it
            # around the listSpec colon: `[name: *]`)
        elif ch in _IDENT_CHARS:
            j = i
            while j < n and path[j] in _IDENT_CHARS:
                j += 1
            toks.append(("IDENT", path[i:j]))
            i = j
        else:
            raise PathError(f"unexpected character {ch!r} at {i}: {path!r}")
    return toks


def parse(path: str) -> list[PathNode]:
    """Parse a location string into path nodes; raises PathError."""
    if not isinstance(path, str) or not path.strip():
        raise PathError("location must be a non-empty string")
    toks = _tokenize(path)
    nodes: list[PathNode] = []
    pos = 0

    def expect(*types: str) -> tuple[str, str]:
        nonlocal pos
        if pos >= len(toks):
            raise PathError(f"unexpected end of path: {path!r}")
        t, text = toks[pos]
        if t not in types:
            raise PathError(
                f"expected one of {types} at token {pos}, got {t!r}: {path!r}")
        pos += 1
        return t, text

    while True:
        _, name = expect("IDENT", "STRING")
        if pos < len(toks) and toks[pos][0] == "[":
            pos += 1
            _, key_field = expect("IDENT", "STRING")
            expect(":")
            t, key_value = expect("IDENT", "STRING", "GLOB")
            expect("]")
            if t == "GLOB":
                nodes.append(ListNode(name, key_field, None, glob=True))
            else:
                if t == "IDENT" and key_value.isdigit():
                    # bare numeric key values are integers (so
                    # [containerPort: 8080] matches the int-typed field
                    # a real Pod carries); quote to force a string
                    key_value = int(key_value)
                nodes.append(ListNode(name, key_field, key_value))
        else:
            nodes.append(ObjectNode(name))
        if pos >= len(toks):
            return nodes
        expect(".")
        if pos >= len(toks):
            raise PathError(f"trailing '.' in path: {path!r}")


def _render_field(name: str) -> str:
    if name and all(c in _IDENT_CHARS for c in name):
        return name
    return '"' + name.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _render_key_value(value) -> str:
    if isinstance(value, int):
        return str(value)
    # a STRING of digits must stay quoted or it would re-parse as int
    if isinstance(value, str) and value.isdigit():
        return '"' + value + '"'
    return _render_field(str(value))


def render(nodes: list[PathNode]) -> str:
    """Canonical string form; parse(render(parse(s))) == parse(s)."""
    out = []
    for node in nodes:
        if isinstance(node, ListNode):
            value = "*" if node.glob else _render_key_value(node.key_value)
            out.append(f"{_render_field(node.name)}"
                       f"[{_render_field(node.key_field)}: {value}]")
        else:
            out.append(_render_field(node.name))
    return ".".join(out)
