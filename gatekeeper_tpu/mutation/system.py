"""The mutation system: ingestion cache, schema-conflict quarantine,
batched applicability, and apply-to-convergence.

Counterpart of the reference's pkg/mutation/system.go + the mutation
schema DB (pkg/mutation/schema): mutators are cached by id
(kind, name); every upsert/remove rebuilds the implied type graph over
ALL cached mutators' location paths and quarantines the ones whose
implied types disagree (a path prefix one mutator traverses as an
object and another as a keyed list). Quarantined mutators are excluded
from application — conflicts surface as a status condition at
ingestion time instead of failing open at apply time.

Application is batched: applicability (spec.match × applyTo) for a
whole admission micro-batch is computed through the same vectorized
target-matcher path the validation webhook uses (target/batch.py
match_masks — one signature-grouped sweep instead of R×M predicate
calls), then each matched object is mutated on the host by applying
its mutators in deterministic id order, pass after pass, until a full
pass changes nothing. A pass budget (`max_iterations`) bounds
ping-pong mutator sets: exceeding it raises instead of admitting a
half-mutated object. Convergence doubles as the idempotence proof —
the final pass re-applies every mutator to the already-mutated object
and observes zero changes, so a second webhook trip yields an empty
patch set.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Optional

import numpy as np

from ..target.batch import match_masks
from .mutators import (
    MUTATOR_KINDS,
    MutationError,
    Mutator,
    load_mutator,
    semantic_equal,
)
from .path import ListNode, ObjectNode

DEFAULT_MAX_ITERATIONS = 10

NamespaceLookup = Callable[[str], Optional[dict]]

_OBJECT = "object"
_LIST = "list"


def implied_types(mutator: Mutator) -> list[tuple[tuple, str]]:
    """(path-prefix, implied type) pairs for the conflict graph.

    A keyed-list accessor implies its field is a LIST; a non-terminal
    object node implies an OBJECT. A terminal object node implies
    nothing for Assign/AssignMetadata (the assigned value defines it)
    but LIST for ModifySet (its location names the list itself)."""
    out: list[tuple[tuple, str]] = []
    names: tuple = ()
    last = len(mutator.nodes) - 1
    for i, node in enumerate(mutator.nodes):
        names = names + (node.name,)
        if isinstance(node, ListNode):
            out.append((names, _LIST))
        elif i < last:
            out.append((names, _OBJECT))
        elif mutator.kind == "ModifySet":
            out.append((names, _LIST))
    return out


def _lists_overlap(a: list[str], b: list[str]) -> bool:
    return "*" in a or "*" in b or bool(set(a) & set(b))


def _scopes_overlap(a, b) -> bool:
    """Can the two mutators' applyTo scopes select the same object?
    A mutator without applyTo (AssignMetadata) scopes to everything."""
    if a.apply_to is None or b.apply_to is None:
        return True
    for ea in a.apply_to:
        for eb in b.apply_to:
            if (_lists_overlap(ea["groups"], eb["groups"])
                    and _lists_overlap(ea["versions"], eb["versions"])
                    and _lists_overlap(ea["kinds"], eb["kinds"])):
                return True
    return False


class MutationSystem:
    def __init__(self, max_iterations: int = DEFAULT_MAX_ITERATIONS):
        self.max_iterations = max_iterations
        self._lock = threading.RLock()
        self._mutators: dict[tuple, Mutator] = {}
        self._quarantine: dict[tuple, str] = {}  # id -> conflict reason
        # appliable mutators in id order, rebuilt on every effective
        # upsert/remove — active() is on the per-request webhook hot
        # path and must not re-sort the library each call. Treated as
        # immutable by readers.
        self._active_list: list[Mutator] = []
        # mutator-change observer (N-engine replication hook): called
        # after an EFFECTIVE upsert/remove with (op, plain object) —
        # semantic-equal dedupes do not notify
        self.on_change = None

    def _notify(self, op: str, obj) -> None:
        cb = self.on_change
        if cb is None or obj is None:
            return
        try:
            cb(op, obj)
        except Exception:
            import logging

            logging.getLogger("gatekeeper_tpu.mutation").warning(
                "mutator change notification failed", exc_info=True)

    # ------------------------------------------------------------ cache

    def upsert(self, obj: dict) -> tuple[Mutator, set]:
        """Validate + cache a mutator CR. Returns (mutator, ids whose
        quarantine state changed — including this one when it enters
        quarantined). Raises MutationError on an invalid spec."""
        mutator = load_mutator(obj)
        with self._lock:
            prev = self._mutators.get(mutator.id)
            if prev is not None and semantic_equal(prev.obj, mutator.obj):
                return prev, set()
            self._mutators[mutator.id] = mutator
            changed = self._recompute_conflicts()
        self._notify("upsert_mutator", obj)
        return mutator, changed

    def remove(self, mid: tuple) -> set:
        """Drop a mutator by (kind, name); returns changed-quarantine
        ids (removals can clear conflicts on surviving mutators)."""
        with self._lock:
            if self._mutators.pop(tuple(mid), None) is None:
                return set()
            changed = self._recompute_conflicts()
        self._notify("remove_mutator", {"kind": mid[0],
                                        "metadata": {"name": mid[1]}})
        return changed

    def get(self, mid: tuple) -> Optional[Mutator]:
        with self._lock:
            return self._mutators.get(tuple(mid))

    def mutators(self) -> list[Mutator]:
        with self._lock:
            return [self._mutators[k] for k in sorted(self._mutators)]

    def sources(self) -> list[dict]:
        """Raw CRs of every cached mutator in id order, for the
        warm-restart library snapshot (restore replays them through
        upsert, re-running validation and conflict detection)."""
        with self._lock:
            return [copy.deepcopy(self._mutators[k].obj)
                    for k in sorted(self._mutators)]

    def active(self) -> list[Mutator]:
        """Appliable mutators in deterministic id order (quarantined
        ones excluded). O(1): returns the cached snapshot — do not
        mutate it."""
        return self._active_list

    def conflicts(self) -> dict[tuple, str]:
        with self._lock:
            return dict(self._quarantine)

    def counts(self) -> dict[str, int]:
        """Gauge fodder: cached mutators by kind plus the conflict set."""
        with self._lock:
            out = {k: 0 for k in MUTATOR_KINDS}
            for kind, _ in self._mutators:
                out[kind] = out.get(kind, 0) + 1
            out["conflicting"] = len(self._quarantine)
            return out

    def _recompute_conflicts(self) -> set:
        """Rebuild the implied type graph; returns ids whose quarantine
        state flipped or whose conflict reason changed. Caller holds
        the lock.

        Type disagreement alone is not enough: the implied schemas are
        scoped by applyTo (as the reference's schema DB binds per GVK),
        so two mutators that can never touch the same kind of object —
        say a Pod mutator treating spec.containers as a list and a CRD
        mutator treating its own spec.containers as an object — do NOT
        quarantine each other."""
        by_prefix: dict[tuple, dict[str, list[tuple]]] = {}
        for mid, m in self._mutators.items():
            for prefix, t in implied_types(m):
                by_prefix.setdefault(prefix, {}).setdefault(t, []).append(mid)
        quarantine: dict[tuple, str] = {}
        for prefix, types in sorted(by_prefix.items()):
            if len(types) < 2:
                continue
            dotted = ".".join(prefix)
            lists = sorted(types.get(_LIST, ()))
            objects = sorted(types.get(_OBJECT, ()))
            for side, mine, other in ((_LIST, lists, objects),
                                      (_OBJECT, objects, lists)):
                other_side = _OBJECT if side == _LIST else _LIST
                for mid in mine:
                    opp = [o for o in other
                           if _scopes_overlap(self._mutators[mid],
                                              self._mutators[o])]
                    if opp:
                        quarantine.setdefault(
                            mid,
                            f"schema conflict at {dotted!r}: {side} per "
                            f"{mid} vs {other_side} per {opp}")
        # changed = membership flips AND reason-text changes: a third
        # mutator joining an existing conflict must refresh the original
        # pair's status conditions too
        changed = {mid for mid in set(quarantine) | set(self._quarantine)
                   if quarantine.get(mid) != self._quarantine.get(mid)}
        self._quarantine = quarantine
        self._active_list = [self._mutators[k]
                             for k in sorted(self._mutators)
                             if k not in quarantine]
        return changed

    # ---------------------------------------------------- applicability

    def match_mask(self, mutators: list[Mutator], reviews: list[dict],
                   lookup_ns: NamespaceLookup) -> np.ndarray:
        """mask[R, M]: which mutators apply to which reviews. spec.match
        rides the vectorized constraint matcher (signature-grouped, one
        predicate call per (projection, mutator) instead of R×M);
        applyTo is AND-ed per distinct review GVK."""
        R, M = len(reviews), len(mutators)
        if not R or not M:
            return np.zeros((R, M), dtype=bool)
        shaped = [{"spec": {"match": m.match}} for m in mutators]
        mask = match_masks(shaped, reviews, lookup_ns)
        by_gvk: dict[tuple, list[int]] = {}
        for r, review in enumerate(reviews):
            kind = review.get("kind")
            kind = kind if isinstance(kind, dict) else {}
            gvk = (kind.get("group") or "", kind.get("version") or "",
                   kind.get("kind") or "")
            by_gvk.setdefault(gvk, []).append(r)
        for gvk, rows in by_gvk.items():
            cols = [c for c, m in enumerate(mutators)
                    if not m.applies_to_gvk(*gvk)]
            if cols:
                mask[np.ix_(rows, cols)] = False
        return mask

    # ------------------------------------------------------ application

    def mutate_batch(self, reviews: list[dict],
                     lookup_ns: Optional[NamespaceLookup] = None
                     ) -> list:
        """One micro-batch: returns per review either the mutated object
        (a fresh deep copy), None when nothing applies (no object — e.g.
        DELETE — or no matching mutator: the caller skips the deep copy
        AND the patch diff for the common all-allow case), or the
        MutationError raised for that review."""
        lookup = lookup_ns or (lambda name: None)
        active = self.active()
        out: list = []
        mask = self.match_mask(active, reviews, lookup) if active else None
        for r, review in enumerate(reviews):
            obj = review.get("object")
            mine = [active[int(c)] for c in np.flatnonzero(mask[r])] \
                if mask is not None else []
            if not isinstance(obj, dict) or not mine:
                out.append(None)
                continue
            try:
                out.append(self._converge(obj, mine))
            except MutationError as e:
                out.append(e)
        return out

    def mutate(self, review: dict,
               lookup_ns: Optional[NamespaceLookup] = None):
        """Single-review convenience over mutate_batch; raises the
        per-review MutationError instead of returning it. None means
        nothing applied."""
        res = self.mutate_batch([review], lookup_ns)[0]
        if isinstance(res, MutationError):
            raise res
        return res

    def _converge(self, obj: dict, mutators: list[Mutator]) -> dict:
        out = copy.deepcopy(obj)
        if not mutators:
            return out
        for _ in range(max(1, self.max_iterations)):
            changed = False
            for m in mutators:
                changed = m.apply(out) or changed
            if not changed:
                return out
        raise MutationError(
            f"mutation did not converge after {self.max_iterations} "
            f"iterations (mutators: "
            f"{sorted('/'.join(m.id) for m in mutators)})")
