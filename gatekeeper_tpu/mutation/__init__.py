"""TPU-batched mutating admission: Assign / AssignMetadata / ModifySet.

The mutation counterpart of the validation stack — mutator CRD types
with location-path parsing (`path.py`), per-kind apply semantics
(`mutators.py`), the ingestion cache + schema-conflict quarantine +
apply-to-convergence engine (`system.py`), and RFC-6902 patch
generation (`patch.py`). The `/v1/mutate` webhook endpoint rides the
same micro-batcher and vectorized target-matcher as validation
(control/webhook.py MutationHandler).
"""

from .mutators import (
    MUTATOR_GROUP,
    MUTATOR_KINDS,
    AssignMetadataMutator,
    AssignMutator,
    ModifySetMutator,
    MutationError,
    Mutator,
    load_mutator,
)
from .patch import apply_patch, json_patch
from .path import ListNode, ObjectNode, PathError, parse, render
from .system import DEFAULT_MAX_ITERATIONS, MutationSystem, implied_types

__all__ = [
    "MUTATOR_GROUP",
    "MUTATOR_KINDS",
    "AssignMetadataMutator",
    "AssignMutator",
    "DEFAULT_MAX_ITERATIONS",
    "ListNode",
    "ModifySetMutator",
    "MutationError",
    "MutationSystem",
    "Mutator",
    "ObjectNode",
    "PathError",
    "apply_patch",
    "implied_types",
    "json_patch",
    "load_mutator",
    "parse",
    "render",
]
