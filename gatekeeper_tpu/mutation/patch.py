"""RFC-6902 JSONPatch generation (and application, for tests/round-trips).

The mutate webhook responds with the minimal add/replace/remove set
turning the request object into the mutated object (the reference
returns the same via admission.PatchResponseFromRaw → apimachinery's
CreateTwoWayMergePatch equivalent). Ops are emitted deterministically:
dict keys in sorted order, list tails removed highest-index-first so
the patch applies cleanly left to right.
"""

from __future__ import annotations

from typing import Any


def escape_pointer(seg: str) -> str:
    """RFC-6901 token escaping."""
    return seg.replace("~", "~0").replace("/", "~1")


def unescape_pointer(seg: str) -> str:
    return seg.replace("~1", "/").replace("~0", "~")


def _diff(before: Any, after: Any, path: str, ops: list[dict]) -> None:
    if before == after:
        return
    if isinstance(before, dict) and isinstance(after, dict):
        for k in sorted(before):
            if k not in after:
                ops.append({"op": "remove",
                            "path": f"{path}/{escape_pointer(str(k))}"})
        for k in sorted(after):
            sub = f"{path}/{escape_pointer(str(k))}"
            if k not in before:
                ops.append({"op": "add", "path": sub, "value": after[k]})
            else:
                _diff(before[k], after[k], sub, ops)
        return
    if isinstance(before, list) and isinstance(after, list):
        common = min(len(before), len(after))
        for i in range(common):
            _diff(before[i], after[i], f"{path}/{i}", ops)
        for i in range(common, len(after)):
            ops.append({"op": "add", "path": f"{path}/{i}",
                        "value": after[i]})
        for i in range(len(before) - 1, common - 1, -1):
            ops.append({"op": "remove", "path": f"{path}/{i}"})
        return
    ops.append({"op": "replace", "path": path, "value": after})


def json_patch(before: Any, after: Any) -> list[dict]:
    """RFC-6902 op list; [] when the objects are equal."""
    ops: list[dict] = []
    _diff(before, after, "", ops)
    return ops


def apply_patch(obj: Any, ops: list[dict]) -> Any:
    """Apply an RFC-6902 patch (add/replace/remove subset) to a deep copy
    of `obj` — the differential oracle for json_patch in tests."""
    import copy as _copy

    doc = _copy.deepcopy(obj)
    for op in ops:
        segs = [unescape_pointer(s) for s in op["path"].split("/")[1:]]
        if not segs:
            if op["op"] in ("add", "replace"):
                doc = _copy.deepcopy(op["value"])
                continue
            raise ValueError("cannot remove the whole document")
        parent = doc
        for s in segs[:-1]:
            parent = parent[int(s)] if isinstance(parent, list) else parent[s]
        leaf = segs[-1]
        kind = op["op"]
        if isinstance(parent, list):
            idx = len(parent) if leaf == "-" else int(leaf)
            if kind == "add":
                parent.insert(idx, _copy.deepcopy(op["value"]))
            elif kind == "replace":
                parent[idx] = _copy.deepcopy(op["value"])
            else:
                del parent[idx]
        else:
            if kind == "add" or kind == "replace":
                parent[leaf] = _copy.deepcopy(op["value"])
            else:
                del parent[leaf]
    return doc
