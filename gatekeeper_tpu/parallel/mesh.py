"""Device mesh construction and audit-sweep sharding.

The audit cross-product (objects × constraints, SURVEY.md §2.5) shards
over a 2-D mesh:

  * "data"  — the object/review axis (N): each device evaluates a slab of
    the cluster inventory. The pure data-parallel dimension; scales to
    multi-host over DCN with no cross-device traffic during evaluation.
  * "model" — the constraint axis (C): parameter tensors shard across
    devices when constraint sets are large (the analog of tensor/model
    parallelism; verdict aggregation all-gathers over ICI).

The evaluator function itself (ir/evaljax.py) is pure and shape-static, so
sharding is entirely in the data layout: annotate inputs with
NamedSharding and let XLA insert the collectives (the scaling-book recipe:
pick a mesh, annotate, let the compiler do the rest). shard_map is used
where the collective must be explicit (per-constraint violation counts
psum'd over the data axis in parallel/collectives.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map graduated from jax.experimental in newer releases, and its
# replication-check kwarg was renamed check_rep -> check_vma; this wrap
# is the ONE place that absorbs both differences (evaljax's mesh sweep
# and collectives' audit step both route through it)
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map_wrap(f, mesh, in_specs, out_specs):
    """shard_map with replication checking disabled, under whichever
    kwarg spelling this jax version takes."""
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def make_mesh(devices=None, data: Optional[int] = None,
              model: int = 1) -> Mesh:
    """Mesh over the available devices, data-major.

    Default: all devices on the data axis (objects), model=1. For very
    large constraint sets pass model>1 to shard parameters too.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.array(devices).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def pad_batch(feats: dict, n_mult: int) -> tuple[dict, int]:
    """Pad every [N, ...] feature array so N divides the data axis."""
    out = {}
    n_old = None
    for slot, arrs in feats.items():
        out[slot] = {}
        for name, a in arrs.items():
            n_old = a.shape[0]
            n_new = _pad_to(n_old, n_mult)
            if n_new != n_old:
                pad = [(0, n_new - n_old)] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            out[slot][name] = a
    return out, (n_old if n_old is not None else 0)


def shard_features(feats: dict, mesh: Mesh) -> dict:
    """Place feature arrays sharded on the data axis (leading N dim)."""
    out = {}
    for slot, arrs in feats.items():
        out[slot] = {}
        for name, a in arrs.items():
            spec = P("data", *([None] * (a.ndim - 1)))
            out[slot][name] = jax.device_put(
                a, NamedSharding(mesh, spec))
    return out


def shard_params(params: dict, mesh: Mesh, shard_c: bool = False) -> dict:
    """Constraint tensors: replicated by default; sharded over "model"
    when the constraint set is large."""
    out = {}
    for slot, arrs in params.items():
        out[slot] = {}
        for name, a in arrs.items():
            if shard_c:
                spec = P("model", *([None] * (a.ndim - 1)))
            else:
                spec = P(*([None] * a.ndim))
            out[slot][name] = jax.device_put(a, NamedSharding(mesh, spec))
    return out


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P(*([None] * np.ndim(x)))))
