"""Synthetic audit workloads for benchmarks and multi-chip dry-runs.

Builds the flagship evaluation setup — the K8sRequiredLabels program (the
reference's canonical template, library/general/requiredlabels) compiled to
the tensor IR, with N synthetic namespace objects and C constraints — and
returns everything needed to run the device sweep directly. Mirrors
BASELINE.md configs #1 (1k objects) and #4 (500 × 100k cross-product).
"""

from __future__ import annotations

import random

REQUIRED_LABELS_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8srequiredlabels"},
    "spec": {
        "crd": {"spec": {
            "names": {"kind": "K8sRequiredLabels"},
            "validation": {"openAPIV3Schema": {"properties": {
                "message": {"type": "string"},
                "labels": {"type": "array", "items": {
                    "type": "object", "properties": {
                        "key": {"type": "string"},
                        "allowedRegex": {"type": "string"}}}},
            }}},
        }},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            # independently authored; behaviorally equivalent to the
            # reference template (library/general/requiredlabels/src.rego)
            "rego": """
package k8srequiredlabels

violation[{"msg": msg, "details": {"missing_labels": missing}}] {
  provided := {label | input.review.object.metadata.labels[label]}
  required := {label | label := input.parameters.labels[_].key}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("you must provide labels: %v", [missing])
}

violation[{"msg": msg}] {
  value := input.review.object.metadata.labels[key]
  expected := input.parameters.labels[_]
  expected.key == key
  expected.allowedRegex != ""
  not re_match(expected.allowedRegex, value)
  msg := sprintf("label <%v: %v> does not match the allowed regex %v", [key, value, expected.allowedRegex])
}
""",
        }],
    },
}

# per-key value pools, consistent with the regexes constraints use — a
# healthy cluster where violations are the exception (audit's normal case)
LABEL_POOL = {
    "owner": (["alpha.corp.example", "beta.corp.example"],
              "^[a-z]+.corp.example$"),
    "team": (["payments", "identity", "infra"], "^[a-z]+$"),
    "env": (["prod", "dev"], "^prod$|^dev$"),
    "tier": (["frontend", "backend"], "^[a-z]+$"),
    "region": (["us-east1", "us-west1"], "^us-"),
    "app": (["shop", "ledger"], "^[a-z0-9-]+$"),
    "cost-center": (["cc-100", "cc-200"], "^cc-[0-9]+$"),
    "compliance": (["pci", "sox"], "^[a-z]+$"),
    "zone": (["a", "b"], "^[ab]$"),
    "dept": (["eng", "ops"], "^[a-z]+$"),
}
LABEL_KEYS = list(LABEL_POOL)


def synth_objects(n: int, violate_frac: float = 0.01, seed: int = 0):
    """N namespace objects carrying the full label pool; ~violate_frac of
    them break one label (missing or regex-violating)."""
    rng = random.Random(seed)
    objs = []
    for i in range(n):
        labels = {k: rng.choice(vals) for k, (vals, _) in LABEL_POOL.items()}
        if rng.random() < violate_frac:
            k = rng.choice(LABEL_KEYS)
            if rng.random() < 0.5:
                labels.pop(k)
            else:
                labels[k] = "###BAD###"
        objs.append({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": f"ns-{i}", "labels": labels},
        })
    return objs


def synth_constraints(c: int, seed: int = 1):
    """C requiredlabels constraints drawing keys+regexes from the pool."""
    rng = random.Random(seed)
    out = []
    for i in range(c):
        labels = []
        for k in rng.sample(LABEL_KEYS, rng.randint(1, 3)):
            entry = {"key": k}
            if rng.random() < 0.6:
                entry["allowedRegex"] = LABEL_POOL[k][1]
            labels.append(entry)
        out.append({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels",
            "metadata": {"name": f"req-{i}"},
            "spec": {"parameters": {"labels": labels}},
        })
    return out


def build_eval_setup(n_objects: int, n_constraints: int, seed: int = 0,
                     n_bucket: int | None = None,
                     violate_frac: float = 0.01):
    """-> (driver, compiled_template, feats, params, match_table, derived,
    reviews, constraints). Device arrays not yet placed."""
    from ..client import Backend
    from ..ir import TpuDriver
    from ..ir.features import extract_batch
    from ..ir.params import encode_params
    from ..target import K8sValidationTarget

    driver = TpuDriver()
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    constraints = synth_constraints(n_constraints, seed + 1)
    for c in constraints:
        client.add_constraint(c)
    ct = driver.compiled_for("K8sRequiredLabels")
    assert ct is not None, "flagship template must compile"
    objects = synth_objects(n_objects, violate_frac=violate_frac, seed=seed)
    reviews = [{"kind": {"group": "", "version": "v1", "kind": "Namespace"},
                "name": o["metadata"]["name"], "object": o}
               for o in objects]
    feats, _, _ = extract_batch(ct.program, driver.strtab, reviews,
                                n_bucket=n_bucket)
    cons = driver._constraints("admission.k8s.gatekeeper.sh")
    pd = [(x.get("spec") or {}).get("parameters") or {} for x in cons]
    params = encode_params(ct.program, pd, driver.strtab, driver.match_tables)
    # derived columns + match table materialize AFTER extraction/encoding
    # interned this batch's strings (driver._derived_arrays ordering
    # contract)
    derived = driver._derived_arrays("K8sRequiredLabels", ct)
    table = driver.match_tables.materialize_packed()
    return driver, ct, feats, params, table, derived, reviews, cons
