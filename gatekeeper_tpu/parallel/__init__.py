from .mesh import make_mesh, pad_batch, shard_features, shard_params
from .collectives import make_audit_step

__all__ = ["make_audit_step", "make_mesh", "pad_batch", "shard_features",
           "shard_params"]
