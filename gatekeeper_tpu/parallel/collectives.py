"""Explicit-collective audit aggregation (shard_map over the mesh).

Where mesh.py lets XLA infer collectives from shardings, this module spells
them out with shard_map for the steps whose communication pattern we want
pinned down (and for the multi-chip dry-run to exercise real collectives):

  * per-constraint violation counts: local partial sums on each data shard,
    then psum over "data" (rides ICI within a slice);
  * verdict gather: each data shard's firing pairs all-gathered so the host
    materializes messages once.

This is the TPU-native replacement for the reference's single-goroutine
audit aggregation (pkg/audit/manager.go:337-385 getUpdateListsFromAudit...).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map_wrap


def make_audit_step(eval_fn, mesh: Mesh):
    """Build the sharded audit step: feats sharded on data, params sharded
    on model, derived vocab columns replicated, returns (fires[N, C] fully
    addressable, counts[C] replicated).

    eval_fn(feats, params, table, derived) -> fires[N_local, C_local] must
    be pure.
    """

    fspec = lambda a: P("data", *([None] * (a.ndim - 1)))
    pspec = lambda a: P("model", *([None] * (a.ndim - 1)))

    def step(feats, params, table, derived, n_valid):
        def local(feats_l, params_l, table_l, derived_l, n_valid_l):
            fires = eval_fn(feats_l, params_l, table_l,
                            derived_l)  # [n_loc, c_loc]
            # mask padding rows: this shard covers global rows
            # [idx*n_loc, (idx+1)*n_loc)
            idx = jax.lax.axis_index("data")
            n_loc = fires.shape[0]
            row = idx * n_loc + jnp.arange(n_loc)
            fires = jnp.logical_and(fires, (row < n_valid_l)[:, None])
            # per-constraint totals: partial on this shard, summed over the
            # data axis (ICI psum), replicated over data
            counts = jax.lax.psum(
                jnp.sum(fires, axis=0, dtype=jnp.int32), "data")
            return fires, counts

        feats_specs = jax.tree_util.tree_map(fspec, feats)
        params_specs = jax.tree_util.tree_map(pspec, params)
        # derived columns are vocab-indexed lookup tables — replicated,
        # like the match table
        derived_specs = jax.tree_util.tree_map(lambda a: P(), derived)
        return shard_map_wrap(
            local, mesh=mesh,
            in_specs=(feats_specs, params_specs, P(None, None),
                      derived_specs, P()),
            out_specs=(P("data", "model"), P("model")),
        )(feats, params, table, derived, n_valid)

    return jax.jit(step)
