"""What-if preview: evaluate a candidate ConstraintTemplate/Constraint
against the full cached inventory BEFORE it is enforced.

The TPU-only capability the streaming-audit tentpole unlocks: the
inventory is already resident as encoded feature tensors, so sweeping a
candidate policy over 100k+ objects is one device dispatch — an
interpreter line would pay per-object evaluation and could never answer
interactively. `POST /v1/preview` (and `gatekeeper-tpu preview`) takes a
constraint — plus, optionally, a not-yet-installed template — and
returns violation counts and capped samples, without touching the
serving library.

Isolation: the candidate template is compiled under a CONTENT-HASHED
ALIAS KIND (`<Kind>PV<sha12>`), so every per-kind structure it rides —
interpreter package, device program, match mask, extracted feature rows,
AOT store entries — is namespaced away from the serving library's. No
client generation bump, no decision-cache invalidation, no param-cache
clobber. Repeat previews of the same template content hit the alias's
warm caches (sub-second over 100k objects); inventory churn in between
is absorbed by the same patch journal the incremental audit uses.

Off-path compilation: alias ingestion rides the driver's normal
ingest-time prewarm (AOT deserialize on a background thread) and the
sweep rides the async-warm gate, so a cold preview's XLA compile runs
under the driver's warm semaphore off the serving path — admission and
audit sweeps never block on a preview's COMPILER time. The preview CALL
itself may wait out its own compile; that is the request's cost, not
the plane's. The sweep proper does hold the client evaluation lock
(the same discipline as a full audit sweep), so on a pod that also
serves admission a preview delays concurrent verdicts by the warm
sweep's duration; previews serialize on their own lock so at most one
sweep is ever on that lock, and latency-sensitive deployments point
previews at the audit pod's dedicated --preview-port instead.
"""

from __future__ import annotations

import copy
import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Optional

from ..client.crd import CRDError, create_crd, create_schema, validate_cr
from ..client.rewriter import RewriteError, rewrite_template_modules
from ..client.templates import TemplateError, load_template
from ..client.types import ClientError, MissingTemplateError
from . import jsonio, metrics
from .logging import logger
from .util import DEFAULT_ENFORCEMENT_ACTION, VALID_ENFORCEMENT_ACTIONS

log = logger("preview")

MSG_SIZE_LIMIT = 256  # sample message truncation (audit parity)
DEFAULT_SAMPLE_LIMIT = 20
MAX_SAMPLE_LIMIT = 500


class PreviewError(Exception):
    """Caller error (bad payload, unknown template, invalid
    constraint): answered as HTTP 400, never a 500."""


class PreviewEngine:
    """Transport-independent preview evaluation over a Client.

    Compiled candidates are LRU-cached by template-content hash
    (MAX_COMPILED entries); eviction deletes the alias's modules, which
    drops every per-kind cache the driver held for it."""

    MAX_COMPILED = 8

    def __init__(self, opa, target: Optional[str] = None):
        self.opa = opa
        self.target = target or next(iter(opa.targets))
        self._lock = threading.Lock()
        # previews serialize end-to-end on this lock (compile, LRU
        # eviction, sweep): eviction can therefore never delete the
        # modules of an entry another in-flight preview is still
        # sweeping, and at most ONE preview sweep at a time ever queues
        # on the client evaluation lock admission shares
        self._eval_lock = threading.Lock()
        # content sha -> {"alias", "kind", "crd", "prefix", "handler"}
        self._compiled: "OrderedDict[str, dict]" = OrderedDict()

    # ------------------------------------------------------- compilation

    def _ensure_template(self, template_raw: Optional[dict],
                         kind: str) -> tuple[dict, bool]:
        """Compile the candidate template (or the ingested one for
        `kind`) under its content-hashed alias. Returns (entry, cold)."""
        if template_raw is not None:
            try:
                ct = load_template(template_raw)
            except TemplateError as e:
                raise PreviewError(f"invalid template: {e}") from None
        else:
            try:
                ct = self.opa.get_template(kind)
            except (MissingTemplateError, ClientError):
                raise PreviewError(
                    f"no ingested template for kind {kind!r}; include "
                    "the candidate template in the request") from None
        raw = ct.raw if isinstance(ct.raw, dict) else {}
        content = raw.get("spec") or [
            ct.name, ct.kind, ct.validation_schema,
            [(t.target, t.rego, t.libs) for t in ct.targets]]
        sha = hashlib.sha256(json.dumps(
            content, sort_keys=True,
            default=str).encode()).hexdigest()[:12]
        with self._lock:
            ent = self._compiled.get(sha)
            if ent is not None:
                self._compiled.move_to_end(sha)
                return ent, False
            if len(ct.targets) != 1:
                raise PreviewError("template must have exactly 1 target")
            tspec = ct.targets[0]
            handler = self.opa.targets.get(tspec.target)
            if handler is None:
                raise PreviewError(
                    f"target {tspec.target!r} is not recognized")
            alias = f"{ct.kind}PV{sha}"
            try:
                crd = create_crd(ct, create_schema(
                    ct, handler.match_schema()))
                modules = rewrite_template_modules(
                    tspec.target, alias, tspec.rego, tspec.libs,
                    allowed_externs=self.opa.allowed_data_fields,
                    source_name=f"preview:{ct.name}")
            except (CRDError, RewriteError) as e:
                raise PreviewError(f"template does not compile: {e}") \
                    from None
            prefix = f'templates["{tspec.target}"]["{alias}"]'
            # under the client lock: module installation must not race a
            # library ingestion touching the driver's shared tables
            with self.opa._lock:
                self.opa.driver.put_modules(prefix, modules)
            ent = {"alias": alias, "kind": ct.kind, "crd": crd,
                   "prefix": prefix, "handler": handler,
                   "target": tspec.target}
            self._compiled[sha] = ent
            while len(self._compiled) > self.MAX_COMPILED:
                _, old = self._compiled.popitem(last=False)
                with self.opa._lock:
                    try:
                        self.opa.driver.delete_modules(old["prefix"])
                    except Exception:
                        pass  # eviction is best-effort cleanup
            log.info("preview template compiled",
                     details={"kind": ct.kind, "alias": alias})
        return ent, True

    # -------------------------------------------------------- evaluation

    def preview(self, payload: dict) -> dict:
        """Evaluate one candidate. Payload:
          {"constraint": {...},            # required
           "template": {...},              # optional (else: ingested)
           "limit": 20}                    # sample cap
        """
        t0 = time.monotonic()
        constraint = payload.get("constraint")
        if not isinstance(constraint, dict):
            raise PreviewError('payload needs a "constraint" object')
        template = payload.get("template")
        if template is not None and not isinstance(template, dict):
            raise PreviewError('"template" must be an object when given')
        try:
            limit = int(payload.get("limit", DEFAULT_SAMPLE_LIMIT))
        except (TypeError, ValueError):
            raise PreviewError('"limit" must be an integer') from None
        limit = min(max(limit, 0), MAX_SAMPLE_LIMIT)
        kind = constraint.get("kind") or ""
        if template is not None:
            tkind = ((template.get("spec") or {}).get("crd") or {}) \
                .get("spec", {}).get("names", {}).get("kind") or kind
            kind = kind or tkind
            if kind and tkind and kind != tkind:
                raise PreviewError(
                    f"constraint kind {kind!r} does not match the "
                    f"template's CRD kind {tkind!r}")
        if not kind:
            raise PreviewError("constraint has no kind")
        spec = constraint.get("spec")
        spec = spec if isinstance(spec, dict) else {}
        action = spec.get("enforcementAction") or DEFAULT_ENFORCEMENT_ACTION
        if action not in VALID_ENFORCEMENT_ACTIONS:
            raise PreviewError(
                f"invalid enforcementAction {action!r}; must be one of "
                f"{VALID_ENFORCEMENT_ACTIONS}")
        with self._eval_lock:
            ent, cold = self._ensure_template(template, kind)
            # validate the candidate against the template's CRD + match
            # schema exactly as ingestion would (kind/apiVersion
            # defaulted: a preview payload is allowed to be minimal)
            con = copy.deepcopy(constraint)
            con.setdefault("kind", kind)
            con.setdefault("apiVersion",
                           "constraints.gatekeeper.sh/v1beta1")
            (con.setdefault("metadata", {})).setdefault("name", "preview")
            try:
                validate_cr(con, ent["crd"])
                ent["handler"].validate_constraint(con)
            except (CRDError, ClientError, ValueError) as e:
                raise PreviewError(f"invalid constraint: {e}") from None
            alias_con = copy.deepcopy(con)
            alias_con["kind"] = ent["alias"]
            driver = self.opa.driver
            # the sweep holds the client evaluation lock — the same
            # discipline as a full audit sweep (Client.audit), so an
            # admission review on a colocated webhook pod queues
            # behind it for the warm sweep's duration (compile time
            # is already off this path via the warm gate)
            with self.opa._lock:
                n_reviews = len(driver._inventory_reviews(self.target))
                if hasattr(driver, "audit_kind"):
                    results, path = driver.audit_kind(
                        self.target, ent["alias"], [alias_con])
                else:
                    results = self._interp_eval(ent["alias"], [alias_con])
                    path = "interp"
        dt = time.monotonic() - t0
        metrics.report_preview("ok", dt)
        out = {
            "kind": kind,
            "constraint": (con.get("metadata") or {}).get("name"),
            "enforcementAction": action,
            "violations": len(results),
            "reviewed": n_reviews,
            "path": path,
            "cold": cold,
            "duration_s": round(dt, 4),
            "samples": self._samples(results, action, limit),
        }
        log.info("what-if preview evaluated",
                 details={k: out[k] for k in
                          ("kind", "violations", "reviewed", "path",
                           "cold", "duration_s")})
        return out

    def _interp_eval(self, alias: str, cons: list) -> list:
        """Pure-interpreter sweep (drivers without audit_kind; also the
        differential oracle the preview tests compare against)."""
        import numpy as np

        from ..target.batch import match_masks

        d = self.opa.driver
        reviews = d._inventory_reviews(self.target)
        lookup_ns = d._namespace_lookup(self.target)
        inventory = d._inventory_tree(self.target)
        mask = match_masks(cons, reviews, lookup_ns)
        out = []
        for r_idx, c_idx in zip(*np.nonzero(mask)):
            constraint = cons[int(c_idx)]
            spec = constraint.get("spec")
            spec = spec if isinstance(spec, dict) else {}
            out.extend(d._eval_template_violations(
                self.target, constraint, reviews[int(r_idx)],
                spec.get("enforcementAction") or "deny", inventory,
                None))
        return out

    @staticmethod
    def _samples(results: list, action: str, limit: int) -> list:
        entries = []
        for r in results[:limit]:
            # interpreter-path results carry the object on the review
            # (resource stays None there); prefer resource when set
            review = getattr(r, "review", None) or {}
            res = r.resource or review.get("object") or {}
            meta = res.get("metadata") or {}
            msg = r.msg
            if len(msg.encode()) > MSG_SIZE_LIMIT:
                msg = msg.encode()[:MSG_SIZE_LIMIT].decode("utf-8",
                                                           "ignore")
            entry = {"message": msg, "enforcementAction": action,
                     "kind": (res.get("kind")
                              or (review.get("kind") or {}).get("kind")),
                     "name": meta.get("name") or review.get("name"),
                     "namespace": (meta.get("namespace")
                                   or review.get("namespace"))}
            entries.append({k: v for k, v in entry.items()
                            if v is not None})
        return entries

    # --------------------------------------------------------- transport

    def handle_http(self, body: bytes) -> tuple[int, bytes]:
        """(status, json payload) for the /v1/preview endpoint."""
        try:
            payload = jsonio.loads(body)
        except ValueError:
            metrics.report_preview("invalid", 0.0)
            return 400, b'{"error": "request body is not valid JSON"}'
        if not isinstance(payload, dict):
            metrics.report_preview("invalid", 0.0)
            return 400, b'{"error": "request body must be an object"}'
        try:
            out = self.preview(payload)
        except PreviewError as e:
            metrics.report_preview("invalid", 0.0)
            return 400, jsonio.dumps_bytes({"error": str(e)})
        except Exception as e:
            # ALL infrastructure-failure classes count here — compile
            # (put_modules), validation surprises, driver eval — so the
            # outcome="error" counter matches the 500s callers see
            metrics.report_preview("error", 0.0)
            log.error("preview evaluation failed", details=str(e))
            return 500, jsonio.dumps_bytes({"error": str(e)})
        return 200, jsonio.dumps_bytes(out)
