"""Admission webhooks: /v1/admit, /v1/admitlabel, and /v1/mutate.

Counterpart of the reference pkg/webhook/policy.go + namespacelabel.go +
mutation.go, with one structural change (BASELINE config #5): requests are
MICRO-BATCHED — handler threads enqueue reviews and a flusher thread ships
whole batches through the driver's vectorized review_batch, so admission
latency rides the batched evaluator instead of per-request interpretation.
The mutating webhook rides the same batcher: applicability for the whole
micro-batch is computed in one vectorized matcher sweep, then the host
applies the matched mutators to convergence and answers with an RFC-6902
JSONPatch (MutationHandler below).

Behavior parity:
  * self-service-account requests short-circuit to allow (policy.go:122-124)
  * DELETE reviews evaluate oldObject as object (policy.go:126-141)
  * gatekeeper's own resources are validated structurally (CreateCRD /
    ValidateConstraint), not policy-evaluated (policy.go:237-287)
  * the request namespace is fetched and sideloaded for namespaceSelector
    resolution (policy.go:310-317)
  * only `deny` enforcement produces deny messages; dryrun only logs
    (policy.go:194-217); --log-denies
  * per-(user, kind) tracing via the Config CRD (policy.go:290-309)
  * fail-open stance is deployment-level (failurePolicy: Ignore), so any
    internal error here returns allow with a warning status
"""

from __future__ import annotations

import base64
import json
import ssl
import threading
import time
from typing import Any, Callable, Optional

from ..client import Client
from ..target.handler import AugmentedReview
from ..utils import faults
from . import jsonio, metrics
from . import trace as gtrace
from .config_types import trace_enabled
from .kube import NotFound
from .logging import logger
from .util import DEFAULT_ENFORCEMENT_ACTION, validate_enforcement_action

log = logger("webhook")

TEMPLATE_GROUP = "templates.gatekeeper.sh"
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"
IGNORE_LABEL = "admission.gatekeeper.sh/ignore"
SERVICE_ACCOUNT = "system:serviceaccount:gatekeeper-system:gatekeeper-admin"

# the API server defaults webhook timeoutSeconds to 10 and caps it at 30
DEFAULT_WEBHOOK_TIMEOUT_S = 10.0
MAX_WEBHOOK_TIMEOUT_S = 30.0


class AdmissionDeadline(TimeoutError):
    """The request's propagated deadline expired before a verdict."""


class AdmissionShed(Exception):
    """The request was refused at enqueue time (queue full / draining)."""


def go_duration_s(text: Optional[str]) -> Optional[float]:
    """Parse the API server's Go-duration webhook timeout ('5s', '30s',
    '500ms', '1m10s') or a bare float; None when absent/unparseable."""
    import re

    if not text:
        return None
    m = re.fullmatch(
        r"(?:(\d+)h)?(?:(\d+)m)?(?:(\d+(?:\.\d+)?)s)?(?:(\d+)ms)?", text)
    if m and any(m.groups()):
        h, mins, secs, ms = m.groups()
        return (int(h or 0) * 3600 + int(mins or 0) * 60
                + float(secs or 0) + int(ms or 0) / 1000.0)
    try:
        return float(text)
    except ValueError:
        return None


def route_path(path: str) -> Optional[str]:
    """One routing rule for every serving topology (in-process server,
    backplane engine, frontends): the admitlabel prefix must be checked
    BEFORE admit (shared prefix), and unknown paths are None."""
    if path.startswith("/v1/admitlabel"):
        return "admitlabel"
    if path.startswith("/v1/admit"):
        return "admit"
    if path.startswith("/v1/mutate"):
        return "mutate"
    if path.startswith("/v1/preview"):
        return "preview"
    if path.startswith("/v1/auditslice"):
        return "auditslice"
    return None


def parse_timeout_query(query: str) -> Optional[float]:
    """The webhook timeout from a request's URL query string.

    admission.k8s.io/v1 carries NO timeoutSeconds in the body — the API
    server conveys its budget only as `?timeout=5s`. Tolerates the wild:
    percent-encoded values, duplicate pairs (first parseable wins), bare
    keys, and malformed fragments never raise."""
    if not query:
        return None
    from urllib.parse import parse_qsl

    try:
        pairs = parse_qsl(query, keep_blank_values=True,
                          strict_parsing=False)
    except ValueError:  # pragma: no cover - parse_qsl is lenient
        return None
    for k, v in pairs:
        if k != "timeout":
            continue
        t = go_duration_s(v)
        if t is not None and t > 0:
            return t
    return None


def request_deadline(request: dict, default_s: float =
                     DEFAULT_WEBHOOK_TIMEOUT_S) -> float:
    """Absolute monotonic deadline for one AdmissionReview: the request's
    timeoutSeconds (defaulting like the API server does) minus a safety
    margin, so the verdict ships BEFORE the API server gives up and
    applies the deployed failurePolicy to a connection we already paid
    for."""
    t = request.get("timeoutSeconds")
    try:
        t = float(t) if t is not None else float(default_s)
    except (TypeError, ValueError):
        t = float(default_s)
    t = min(max(t, 0.5), MAX_WEBHOOK_TIMEOUT_S)
    margin = min(1.0, 0.2 * t)
    return time.monotonic() + t - margin


class _Pending:
    __slots__ = ("review", "done", "results", "error", "deadline",
                 "trace", "t_submit")

    def __init__(self, review: dict, deadline: float, trace=None):
        self.review = review
        self.done = threading.Event()
        self.results: list = []
        self.error: Optional[Exception] = None
        self.deadline = deadline
        # span context pinned to the entry: the flush stamps this
        # request's batch_seal (submit -> eval start) and evaluate
        # spans. None for unsampled requests — no span objects ride
        # the hot path.
        self.trace = trace
        self.t_submit = 0.0


class MicroBatcher:
    """Deadline-bounded admission batching: collect pending reviews for up
    to `max_wait`, flush them through driver.review_batch as one sweep.

    `evaluate` swaps the flush body: it receives the batch's review list
    and returns one outcome per review (an Exception instance fails just
    that request). The default evaluates violations through the driver;
    the mutation webhook passes MutationSystem.mutate_batch and rides
    the identical collector/flusher pipeline."""

    def __init__(self, opa: Optional[Client], max_wait: float = 0.005,
                 max_batch: int = 256,
                 target: str = "admission.k8s.gatekeeper.sh",
                 evaluate: Optional[Callable[[list], list]] = None,
                 max_queue: int = 0, plane: str = "admission"):
        self.opa = opa
        self.max_wait = max_wait
        self.max_batch = max_batch
        # which plane's batch-economics series this batcher feeds
        # (admission | mutation): the seal/fill attribution read must
        # not mix the two batchers' traffic shapes
        self.plane = plane
        # load-shed depth: beyond this many queued (unsealed) requests,
        # submit() refuses immediately with AdmissionShed instead of
        # queueing into certain deadline expiry. 0 = unbounded.
        self.max_queue = max_queue
        self.target = target
        self._evaluate = evaluate or self._evaluate_violations
        self._queue: list[_Pending] = []
        self._cv = threading.Condition()
        self._stop = threading.Event()
        # collector/flusher pipeline: a sealed batch evaluates while the
        # next one collects, so a request that just missed a batch waits
        # ~one flush instead of up to two
        self._sealed: list[list[_Pending]] = []
        self._scv = threading.Condition()
        # liveness heartbeats, one per loop (a live collector must not
        # mask a wedged flusher): healthy() flags a dead thread or work
        # pending with a stale beat so the k8s liveness probe restarts
        # the pod
        self.heartbeat = time.monotonic()    # collector
        self.fheartbeat = time.monotonic()   # flusher
        self._flushing = False
        self._thread = threading.Thread(target=self._loop, name="batcher",
                                        daemon=True)
        self._thread.start()
        self._fthread = threading.Thread(target=self._flush_loop,
                                         name="batcher-flush", daemon=True)
        self._fthread.start()
        self.batches = 0
        self.batched_requests = 0
        self.timeouts = 0
        self.shed = 0
        # total admitted-but-unanswered requests (queued + sealed +
        # flushing): the shed bound applies to THIS, not just the
        # unsealed queue — the collector seals regardless of flusher
        # backlog, so bounding the queue alone would let overload pile
        # up in _sealed instead
        self._pending = 0

    def set_knobs(self, max_wait: Optional[float] = None,
                  max_batch: Optional[int] = None,
                  max_queue: Optional[int] = None) -> dict:
        """Thread-safe live retuning (the adaptive controller's
        actuation surface, also replicated to engine children as a
        `knobs` control op). Values are sanity-clamped here — the
        controller's declared per-knob bounds are tighter, this floor
        only guards a garbage replication frame. Takes effect at the
        next collection window: the collector re-reads max_wait /
        max_batch at each window start, and the shed bound is
        consulted per enqueue. Returns the resulting values."""
        with self._cv:
            if max_wait is not None:
                self.max_wait = max(0.0, float(max_wait))
            if max_batch is not None:
                self.max_batch = max(1, int(max_batch))
            if max_queue is not None:
                self.max_queue = max(0, int(max_queue))
            self._cv.notify()
        return self.knob_values()

    def knob_values(self) -> dict:
        """The live knob set, in the `knobs` control-op wire shape."""
        return {"max_wait": self.max_wait, "max_batch": self.max_batch,
                "max_queue": self.max_queue}

    def submit_many(self, reviews: list, timeout: float = 60.0,
                    deadline: Optional[float] = None) -> list:
        """Bulk enqueue (streaming ingest): every review joins the
        queue under ONE lock pass and one collector wake-up, so a
        whole B-frame batch seals together instead of trickling in
        submit-by-submit. Returns one entry per review — the results
        list, or an AdmissionShed / AdmissionDeadline / evaluation
        Exception INSTANCE for that review (bulk callers need every
        verdict, so per-item failures never raise out)."""
        now = time.monotonic()
        dl = deadline if deadline is not None else now + timeout
        entries: list = []
        with self._cv:
            stopping = self._stop.is_set()
            for review in reviews:
                if stopping:
                    entries.append(AdmissionShed(
                        "admission batcher is shutting down"))
                    continue
                if self.max_queue and self._pending >= self.max_queue:
                    self.shed += 1
                    metrics.report_admission_shed()
                    entries.append(AdmissionShed(
                        f"admission queue full ({self.max_queue} "
                        "pending)"))
                    continue
                p = _Pending(review, dl)
                p.t_submit = now
                self._pending += 1
                self._queue.append(p)
                entries.append(p)
            if self._queue:
                self._cv.notify()
        outs: list = []
        for p in entries:
            if not isinstance(p, _Pending):
                outs.append(p)  # shed at enqueue
                continue
            if not p.done.wait(max(0.0, dl - time.monotonic())):
                with self._cv:
                    try:
                        self._queue.remove(p)
                        self._pending -= 1
                    except ValueError:
                        pass  # already sealed / mid-flush
                self.timeouts += 1
                metrics.report_batch_timeout()
                outs.append(AdmissionDeadline(
                    "admission deadline expired before the micro-batch "
                    "verdict"))
            elif p.error is not None:
                outs.append(p.error)
            else:
                outs.append(p.results)
        return outs

    def submit(self, review: dict, timeout: float = 60.0,
               deadline: Optional[float] = None, trace=None) -> list:
        """Enqueue and wait for the batched verdict. `deadline` is an
        absolute time.monotonic() instant (propagated from the request's
        timeoutSeconds); without one, `timeout` seconds from now. On
        expiry raises AdmissionDeadline; a full queue or a draining
        batcher raises AdmissionShed without queueing. `trace` (a
        sampled gtrace.Trace) is pinned to the queue entry so the flush
        stamps this request's batch spans."""
        now = time.monotonic()
        p = _Pending(review, deadline if deadline is not None
                     else now + timeout,
                     trace=trace if trace is not None
                     and trace.sampled else None)
        p.t_submit = now
        with self._cv:
            if self._stop.is_set():
                raise AdmissionShed("admission batcher is shutting down")
            if self.max_queue and self._pending >= self.max_queue:
                self.shed += 1
                metrics.report_admission_shed()
                raise AdmissionShed(
                    f"admission queue full ({self.max_queue} pending)")
            self._pending += 1
            self._queue.append(p)
            if len(self._queue) == 1 or len(self._queue) >= self.max_batch:
                # wake the collector only on the first enqueue (it sleeps
                # to the batch deadline anyway) or on a full batch — a
                # notify per submit makes it spin once per caller thread
                self._cv.notify()
        if not p.done.wait(max(0.0, p.deadline - time.monotonic())):
            # nobody will consume the result: drop the entry so a later
            # flush doesn't evaluate (and set results on) an abandoned
            # request; if it already sealed into a batch the flush's
            # done.set() is harmless — the waiter is gone either way
            with self._cv:
                try:
                    self._queue.remove(p)
                    self._pending -= 1  # sealed entries decrement at flush
                except ValueError:
                    pass  # already sealed / mid-flush
            self.timeouts += 1
            metrics.report_batch_timeout()
            raise AdmissionDeadline("admission deadline expired before "
                                    "the micro-batch verdict")
        if p.error is not None:
            raise p.error
        return p.results

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify()
        with self._scv:
            self._scv.notify()

    def drain(self, timeout: float = 10.0) -> bool:
        """Flush everything queued/sealed and wait for the in-flight
        evaluation (graceful shutdown: pending reviews get real verdicts
        instead of dropped sockets). True when fully drained.

        Drained == _pending hit zero: that counter only decrements
        AFTER a verdict is set (or a waiter gave up), so it covers the
        collector's queue->sealed handoff window that probing the two
        queues under their separate locks would race."""
        end = time.monotonic() + timeout
        with self._cv:
            self._cv.notify()
        while time.monotonic() < end:
            with self._cv:
                if self._pending == 0:
                    return True
            time.sleep(0.01)
        return False

    def pending(self) -> int:
        """Admitted-but-unanswered requests (queued + sealed +
        flushing): the depth the --admission-max-queue bound applies
        to, sampled by the saturation gauge probe."""
        with self._cv:
            return self._pending

    def healthy(self, max_stall: float = 30.0) -> bool:
        """Liveness: both pipeline threads alive, and — when a loop has
        work pending — that loop's heartbeat within `max_stall` (a
        flusher wedged in a hung evaluation stops beating while its
        backlog grows, even though the collector keeps running)."""
        if self._stop.is_set():
            return True  # stopped on purpose is not a liveness failure
        if not self._thread.is_alive() or not self._fthread.is_alive():
            return False
        now = time.monotonic()
        with self._cv:
            queued = bool(self._queue)
        with self._scv:
            fbusy = bool(self._sealed) or self._flushing
        if queued and now - self.heartbeat > max_stall:
            return False
        if fbusy and now - self.fheartbeat > max_stall:
            return False
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            seal_reason = "drain"
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self.heartbeat = time.monotonic()
                    self._cv.wait(0.1)
                if self._stop.is_set():
                    batch = self._queue[:]
                    self._queue.clear()
                else:
                    self.heartbeat = time.monotonic()
                    # collection window bounded by BOTH the batch wait
                    # and the tightest member deadline: a batch carrying
                    # a 1s-timeout review must seal in time to evaluate
                    # and answer before that review expires
                    window_end = time.monotonic() + self.max_wait
                    tight = min(p.deadline for p in self._queue)
                    deadline = min(window_end, tight - self.max_wait)
                    while (len(self._queue) < self.max_batch
                           and time.monotonic() < deadline):
                        self._cv.wait(
                            max(0.0, deadline - time.monotonic()))
                    # tightest deadlines seal (and therefore flush)
                    # first; sort is stable, so arrival order holds
                    # within equal deadlines
                    self._queue.sort(key=lambda p: p.deadline)
                    batch = self._queue[: self.max_batch]
                    del self._queue[: len(batch)]
                    # what closed the window: full batch, a member's
                    # propagated deadline, or the wait elapsing — the
                    # seal-reason counter is how "edge-bound trickle"
                    # (max_wait at fill ~0) and "engine-bound" (full
                    # at fill 1.0) read off one scrape
                    if len(batch) >= self.max_batch:
                        seal_reason = "full"
                    elif deadline < window_end:
                        seal_reason = "deadline"
                    else:
                        seal_reason = "max_wait"
            if not batch:
                continue
            metrics.report_batch_seal(
                seal_reason, len(batch) / max(1, self.max_batch),
                plane=self.plane)
            with self._scv:
                self._sealed.append(batch)
                self._scv.notify()

    def _flush_loop(self) -> None:
        while True:
            with self._scv:
                while not self._sealed and not self._stop.is_set():
                    self.fheartbeat = time.monotonic()
                    self._scv.wait(0.1)
                if not self._sealed:
                    if self._stop.is_set():
                        return
                    continue
                batch = self._sealed.pop(0)
                self._flushing = True
            try:
                self._flush(batch)
            finally:
                with self._scv:
                    self._flushing = False
            self.fheartbeat = time.monotonic()

    def _flush(self, batch: list[_Pending]) -> None:
        self.batches += 1
        self.batched_requests += len(batch)
        t_eval0 = time.monotonic()
        try:
            # inside the try: a raise-mode flush fault must error THIS
            # batch (and release its _pending slots), not kill the
            # flusher thread and leak the count toward permanent shed
            faults.fire("webhook.flush")
            outs = self._evaluate([p.review for p in batch])
            t_eval1 = time.monotonic()
            for p, results in zip(batch, outs):
                if isinstance(results, Exception):
                    p.error = results
                else:
                    p.results = results
                if p.trace is not None:
                    self._stamp_spans(p, t_eval0, t_eval1)
                p.done.set()
        except Exception as e:
            t_eval1 = time.monotonic()
            for p in batch:
                p.error = e
                if p.trace is not None:
                    self._stamp_spans(p, t_eval0, t_eval1)
                p.done.set()
        finally:
            with self._cv:
                self._pending -= len(batch)

    @staticmethod
    def _stamp_spans(p: _Pending, t_eval0: float, t_eval1: float) -> None:
        """Batch spans for one sampled member: batch_seal (submit ->
        eval start: collection window + flusher backlog) and evaluate
        (the shared batched evaluation — the same interval for every
        co-batched member, which is exactly the attribution wanted:
        the request DID wait that long for its verdict)."""
        p.trace.add_span("batch_seal", p.t_submit, t_eval0)
        p.trace.add_span("evaluate", t_eval0, t_eval1)

    def _evaluate_violations(self, reviews: list[dict]) -> list:
        driver = self.opa.driver
        handler = self.opa.targets[self.target]
        if hasattr(driver, "review_batch"):
            try:
                outs = driver.review_batch(self.target, reviews)
            except Exception as e:
                # one bad review (or one bad template's eval) must not
                # take down every co-batched admission: isolate by
                # re-evaluating per review, failing only the culprits
                log.warning("batched evaluation failed; isolating per "
                            "review", details=str(e))
                outs = self._evaluate_per_review(driver, reviews)
        else:
            outs = self._evaluate_per_review(driver, reviews)
        for results in outs:
            if isinstance(results, Exception):
                continue
            for r in results:
                handler.handle_violation(r)
        return outs

    def _evaluate_per_review(self, driver, reviews: list[dict]) -> list:
        outs: list = []
        for review in reviews:
            try:
                resp = driver.query(("hooks", self.target, "violation"),
                                    {"review": review})
                outs.append(resp.results)
            except Exception as e:
                outs.append(e)
        return outs


def _envelope(admission_review: dict, response: dict) -> dict:
    """AdmissionReview response envelope. admission.k8s.io/v1 REQUIRES
    the response to echo the request's apiVersion/kind (the v1beta1 API
    server tolerated their absence); both are echoed verbatim with the
    legacy defaults for envelope-free callers."""
    return {
        "apiVersion": admission_review.get("apiVersion")
        or "admission.k8s.io/v1beta1",
        "kind": admission_review.get("kind") or "AdmissionReview",
        "response": response,
    }


# --------------------------------------------------- response encoding

# uid charset the API server actually emits (UUIDs); anything outside it
# takes the full-encoder fallback rather than a hand-rolled escape
_UID_SAFE = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.:")
_ENVELOPE_PREFIXES: dict = {}


def encode_envelope(envelope: dict) -> bytes:
    """Serialize an AdmissionReview response envelope.

    The overwhelmingly common response is a bare allow; its envelope is
    PRESERIALIZED per (apiVersion, kind) and patched with the uid, so
    the hot path is two bytes-joins instead of a full JSON encode. A
    response carrying a status message rides a fragment splice (the
    message is the only part needing real escaping); anything else —
    patches, warnings — falls back to the full encoder."""
    resp = envelope.get("response")
    if isinstance(resp, dict):
        uid = resp.get("uid") or ""
        if isinstance(uid, str) and _UID_SAFE.issuperset(uid):
            keys = set(resp)
            if keys <= {"uid", "allowed"} and resp.get("allowed") is True:
                return (_envelope_prefix(envelope) + uid.encode()
                        + b'","allowed":true}}')
            if keys == {"uid", "allowed", "status"} and \
                    isinstance(resp.get("status"), dict):
                return (_envelope_prefix(envelope) + uid.encode()
                        + b'","allowed":'
                        + (b"true" if resp.get("allowed") else b"false")
                        + b',"status":'
                        + jsonio.dumps_bytes(resp["status"]) + b"}}")
    return jsonio.dumps_bytes(envelope)


def _envelope_prefix(envelope: dict) -> bytes:
    key = (envelope.get("apiVersion"), envelope.get("kind"))
    prefix = _ENVELOPE_PREFIXES.get(key)
    if prefix is None:
        prefix = (b'{"apiVersion":' + jsonio.dumps_bytes(key[0])
                  + b',"kind":' + jsonio.dumps_bytes(key[1])
                  + b',"response":{"uid":"')
        if len(_ENVELOPE_PREFIXES) < 64:  # callers send ~2 shapes ever
            _ENVELOPE_PREFIXES[key] = prefix
    return prefix


def verdict_response(pairs) -> dict:
    """The single authority for mapping (enforcement_action, msg)
    violation pairs to an AdmissionReview response body. Every
    consumer of evaluation results — `/v1/admit`, the bulk paths, the
    offline fleet scan — builds its verdict here, so a scan verdict is
    bit-equal to what admission would have answered for the same
    object."""
    denies = []
    warns = []
    for action, msg in pairs:
        if action == "deny":
            denies.append(msg)
        elif action == "warn":
            warns.append(msg)
    if denies:
        response = {"allowed": False,
                    "status": {"code": 403,
                               "reason": "; ".join(sorted(denies))}}
    else:
        response = {"allowed": True}
    if warns:
        response["warnings"] = sorted(warns)
    return response


# ----------------------------------------------------- decision cache


class DecisionCache:
    """Generation-keyed LRU over admission verdicts.

    Key = (canonical request hash, library generation, namespace-label
    hash). Identical retries and DaemonSet-style object storms (the same
    pod spec admitted once per node) skip evaluation entirely; any
    template/constraint/synced-data change bumps the client generation,
    so every stale entry misses and ages out — there is no explicit
    invalidation path to get wrong. Namespace label edits flip the
    namespace hash the same way."""

    def __init__(self, size: int = 4096):
        from collections import OrderedDict

        self.size = size
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def request_key(request: dict) -> bytes:
        """Canonical hash of the verdict-relevant request fields: uid is
        per-attempt noise and timeoutSeconds is a transport budget —
        neither can change the decision."""
        import hashlib

        slim = {k: v for k, v in request.items()
                if k not in ("uid", "timeoutSeconds")}
        return hashlib.blake2b(jsonio.canonical_bytes(slim),
                               digest_size=16).digest()

    @staticmethod
    def ns_key(ns_obj: Optional[dict]) -> bytes:
        """Hash of the WHOLE sideloaded namespace object: policies can
        key on annotations or any other namespace field (the full
        object rides the review), so labels alone would serve stale
        verdicts across non-label namespace edits."""
        if not ns_obj:
            return b""
        import hashlib

        return hashlib.blake2b(jsonio.canonical_bytes(ns_obj),
                               digest_size=16).digest()

    def get(self, key: tuple) -> Optional[dict]:
        with self._lock:
            resp = self._entries.get(key)
            if resp is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return resp

    def put(self, key: tuple, response: dict) -> None:
        with self._lock:
            self._entries[key] = response
            self._entries.move_to_end(key)
            while len(self._entries) > self.size:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class NeedsEvaluation(Exception):
    """Raised inside a fast=True handle(): the verdict is not in the
    decision cache, so answering requires the (blocking) micro-batch
    path — the caller re-dispatches to a worker thread."""


class ValidationHandler:
    """The /v1/admit logic, transport-independent.

    fail_closed flips the internal-error stance: the deployed
    failurePolicy is Ignore (fail-open) and the default matches it, but
    a cluster that prefers blocking to unvalidated admission runs
    --fail-closed and errors become denies. Either way the decision is
    reported to metrics as status="error", not "allow"."""

    def __init__(self, opa: Client, kube=None,
                 batcher: Optional[MicroBatcher] = None,
                 log_denies: bool = False,
                 validate_enforcement: bool = True,
                 traces_provider=None,
                 fail_closed: bool = False,
                 default_timeout: float = DEFAULT_WEBHOOK_TIMEOUT_S,
                 decision_cache_size: int = 4096,
                 ladder=None):
        self.opa = opa
        self.kube = kube
        self.batcher = batcher or MicroBatcher(opa)
        self.log_denies = log_denies
        self.validate_enforcement = validate_enforcement
        self.traces_provider = traces_provider or (lambda: [])
        self.fail_closed = fail_closed
        self.default_timeout = default_timeout
        self.cache = (DecisionCache(decision_cache_size)
                      if decision_cache_size > 0 else None)
        # degradation ladder (control/adaptive.py DegradationLadder,
        # duck-typed: anything with an int `.rung`). Rung >= 2 serves
        # cache hits + short-circuits only (misses shed per the
        # failure stance); rung >= 3 answers every non-exempt request
        # per the stance immediately. None = never degraded.
        self.ladder = ladder

    def handle(self, admission_review: dict,
               deadline: Optional[float] = None,
               fast: bool = False,
               trace=gtrace.NOOP) -> Optional[dict]:
        """`deadline` (absolute monotonic) overrides the one derived
        from the request body — the backplane engine pins it at frame
        receipt so queueing ahead of this call spends the request's
        budget, not a fresh one.

        fast=True answers ONLY when no blocking work is needed (the
        short-circuits and decision-cache hits); a request that would
        have to evaluate returns None instead, and the caller re-issues
        handle() from a thread that may block. The backplane engine
        serves cache hits inline in its frame-reader thread this way —
        no thread handoff on the hot path.

        `trace` is the request's span context (gtrace.NOOP when
        unsampled): batch spans are stamped through the batcher entry,
        and the OUTCOME — allow/deny/shed/timeout/error — lands on the
        trace either way, so shed storms are diagnosable from the
        flight recorder after the fact."""
        t0 = time.monotonic()
        request = admission_review.get("request") or {}
        if deadline is None:
            deadline = request_deadline(request, self.default_timeout)
        try:
            response = self._decide(request, deadline, fast=fast,
                                    trace=trace)
        except NeedsEvaluation:
            return None
        except Exception as e:
            return self._failure(admission_review, request, e, t0,
                                 trace)
        return self._complete(admission_review, request, response, t0,
                              trace)

    def handle_bulk(self, reviews: list, deadline: float) -> list:
        """STREAMING ingest: many pre-parsed AdmissionReviews in, one
        response envelope (dict) per review out, in order — the
        backplane B-frame path for CI scanners and bulk authorizers.

        One prelude pass per review (short-circuits, decision cache,
        target mapping), then everything that needs evaluation joins
        the shared MicroBatcher in ONE submit_many enqueue, so a bulk
        batch seals together with whatever the HTTP frontends have in
        flight. Per-review failures map to the failure stance exactly
        as on the HTTP path; this method never raises per review."""
        outs: list = [None] * len(reviews)
        pend: list = []
        for i, ar in enumerate(reviews):
            if not isinstance(ar, dict):
                ar = {}
            t0 = time.monotonic()
            request = ar.get("request") or {}
            try:
                pre = self._prelude(request)
            except Exception as e:
                outs[i] = self._failure(ar, request, e, t0)
                continue
            if pre.response is not None:
                outs[i] = self._complete(ar, request, pre.response, t0)
            elif pre.want_trace:
                # traced requests keep their per-request path
                outs[i] = self.handle(ar, deadline=deadline)
            else:
                pend.append((i, ar, request, pre, t0))
        if pend:
            results = self.batcher.submit_many(
                [entry[3].gk_review for entry in pend],
                deadline=deadline)
            for (i, ar, request, pre, t0), res in zip(pend, results):
                if isinstance(res, Exception):
                    outs[i] = self._failure(ar, request, res, t0)
                    continue
                try:
                    response = self._finish(request, pre, res)
                    outs[i] = self._complete(ar, request, response, t0)
                except Exception as e:
                    outs[i] = self._failure(ar, request, e, t0)
        return outs

    # outcome mapping shared by handle() and handle_bulk() ------------

    def _failure(self, admission_review: dict, request: dict, e,
                 t0: float, trace=gtrace.NOOP) -> dict:
        if isinstance(e, AdmissionShed):
            status, code = "shed", 429
        elif isinstance(e, AdmissionDeadline):
            # answer per the failure stance BEFORE the API server's own
            # timeout fires — the caller gets our verdict, not a
            # connection error it has to map through failurePolicy
            status, code = "timeout", 504
        else:
            log.error("admission error", details=str(e))
            status, code = "error", 500
        response = {"allowed": not self.fail_closed,
                    "status": {"code": code, "message": str(e)}}
        return self._complete(admission_review, request, response, t0,
                              trace, status=status)

    def _complete(self, admission_review: dict, request: dict,
                  response: dict, t0: float, trace=gtrace.NOOP,
                  status: Optional[str] = None) -> dict:
        if status is None:
            status = "allow" if response.get("allowed") else "deny"
        metrics.report_request(status, time.monotonic() - t0)
        trace.set_status(status)
        response["uid"] = request.get("uid") or ""
        return _envelope(admission_review, response)

    # decision pipeline: prelude -> evaluate -> finish ----------------

    class _Prelim:
        __slots__ = ("response", "gk_review", "cache_key", "want_trace",
                     "want_dump", "ns_obj", "review")

        def __init__(self):
            self.response = None
            self.gk_review = None
            self.cache_key = None
            self.want_trace = False
            self.want_dump = False
            self.ns_obj = None
            self.review = None

    def _decide(self, request: dict,
                deadline: Optional[float] = None,
                fast: bool = False, trace=gtrace.NOOP) -> dict:
        pre = self._prelude(request, fast=fast, trace=trace)
        if pre.response is not None:
            return pre.response
        if pre.want_trace:
            # traced requests bypass the batcher: the trace is per-request
            # (reference policy.go:290-309)
            resps = self.opa.review(AugmentedReview(pre.review,
                                                    pre.ns_obj),
                                    tracing=True)
            for name, resp in sorted(resps.by_target.items()):
                log.info("request trace", target=name,
                         trace=resp.trace_dump())
            if pre.want_dump:
                log.info("state dump", dump=self.opa.dump())
            results = resps.results()
        else:
            results = self.batcher.submit(pre.gk_review,
                                          deadline=deadline,
                                          trace=trace)
        return self._finish(request, pre, results)

    def _prelude(self, request: dict, fast: bool = False,
                 trace=gtrace.NOOP) -> "_Prelim":
        """Everything before (possibly blocking) evaluation: short-
        circuits, gatekeeper-resource validation, DELETE mapping, the
        namespace sideload, target mapping, and the decision cache.
        Either `.response` is the finished verdict or `.gk_review` is
        ready for the batcher."""
        pre = self._Prelim()
        username = (request.get("userInfo") or {}).get("username")
        t_dec0 = time.monotonic() if trace.sampled else 0.0
        if username == SERVICE_ACCOUNT:
            pre.response = {"allowed": True}
            return pre
        rung = self.ladder.rung if self.ladder is not None else 0
        if rung >= 3:
            # fail-stance rung: the plane is past the point where
            # evaluating (or even consulting the cache) helps —
            # answer per the configured failure stance immediately.
            # Raising (not returning a response) routes through
            # _failure, so status=shed accounting and the stance
            # mapping stay on the one shared path.
            raise AdmissionShed(
                "degraded (fail_stance): admission answered per "
                "failure stance without evaluation")
        kind = request.get("kind") or {}
        group = kind.get("group") or ""
        if group in (TEMPLATE_GROUP, CONSTRAINT_GROUP):
            pre.response = self._validate_gatekeeper_resource(request,
                                                              group)
            return pre
        review = dict(request)
        if (request.get("operation") == "DELETE"
                and not request.get("object")
                and request.get("oldObject") is not None):
            # evaluate what is being deleted (policy.go:126-141)
            review["object"] = request.get("oldObject")
        pre.review = review
        ns_name = request.get("namespace")
        if ns_name and self.kube is not None:
            if fast:
                # the namespace fetch may hit the API server: not a
                # fast-path operation (a future informer cache would
                # lift this)
                raise NeedsEvaluation()
            try:
                pre.ns_obj = self.kube.get(("", "v1", "Namespace"),
                                           ns_name)
            except NotFound:
                pre.ns_obj = None
        handled, gk_review = self.opa.targets[
            "admission.k8s.gatekeeper.sh"].handle_review(
                AugmentedReview(review, pre.ns_obj))
        if not handled:
            pre.response = {"allowed": True}
            return pre
        pre.gk_review = gk_review
        pre.want_trace, pre.want_dump = trace_enabled(
            self.traces_provider(), username,
            (group, kind.get("version") or "", kind.get("kind") or ""))
        if self.cache is not None and not pre.want_trace:
            # generation read BEFORE evaluation: a library update racing
            # the eval stores the old verdict under the old generation,
            # which no future lookup consults
            pre.cache_key = (DecisionCache.request_key(request),
                             self.opa.generation,
                             DecisionCache.ns_key(pre.ns_obj))
            cached = self.cache.get(pre.cache_key)
            if cached is not None and (cached.get("allowed")
                                       or not self.log_denies):
                metrics.report_decision_cache("hit")
                if trace.sampled:
                    trace.add_span("cache_hit", t_dec0,
                                   time.monotonic())
                # shallow copy: the caller patches uid into the response
                pre.response = dict(cached)
                return pre
            if rung >= 2:
                # cache-only rung: hits (above) still serve at full
                # speed; a miss would need evaluation the degraded
                # plane is protecting — shed it, on the fast path too
                # (a shed needs no blocking work)
                raise AdmissionShed(
                    "degraded (cache_only): decision-cache miss shed "
                    "without evaluation")
            if fast:
                raise NeedsEvaluation()  # miss reported by the re-issue
            metrics.report_decision_cache("miss")
        elif self.cache is not None:
            if rung >= 2:
                raise AdmissionShed(
                    "degraded (cache_only): uncacheable request shed "
                    "without evaluation")
            if fast:
                raise NeedsEvaluation()
            metrics.report_decision_cache("bypass")
        if rung >= 2:
            raise AdmissionShed(
                "degraded (cache_only): evaluation path disabled")
        if fast:
            raise NeedsEvaluation()  # cache disabled: evaluation ahead
        return pre

    def _finish(self, request: dict, pre: "_Prelim",
                results: list) -> dict:
        username = (request.get("userInfo") or {}).get("username")
        if self.log_denies:
            for r in results:
                log.info(
                    "violation",
                    event_type="violation",
                    constraint_name=(r.constraint or {}).get(
                        "metadata", {}).get("name"),
                    constraint_kind=(r.constraint or {}).get("kind"),
                    constraint_action=r.enforcement_action,
                    resource_namespace=request.get("namespace"),
                    resource_name=request.get("name"),
                    request_username=username,
                    details=r.msg,
                )
        # enforcementAction: warn (reference policy.go:194-217 line):
        # the verdict stays allowed and the violation rides the
        # AdmissionReview warnings field, which kubectl surfaces as a
        # client-side Warning header — verdict_response owns the
        # mapping
        response = verdict_response(
            (r.enforcement_action, r.msg) for r in results)
        if pre.cache_key is not None and (not self.log_denies
                                          or not results):
            # under --log-denies a cached answer must not swallow audit
            # log lines: only violation-FREE responses are cached (deny,
            # warn, and dryrun results all log per request)
            self.cache.put(pre.cache_key, dict(response))
        return response

    def _validate_gatekeeper_resource(self, request: dict,
                                      group: str) -> dict:
        if request.get("operation") == "DELETE":
            return {"allowed": True}
        obj = request.get("object") or {}
        try:
            if group == TEMPLATE_GROUP:
                self.opa.create_crd(obj)
            else:
                action = (obj.get("spec") or {}).get("enforcementAction") \
                    or DEFAULT_ENFORCEMENT_ACTION
                if self.validate_enforcement:
                    validate_enforcement_action(action)
                self.opa.validate_constraint(obj)
        except Exception as e:
            return {"allowed": False,
                    "status": {"code": 422, "reason": str(e)}}
        return {"allowed": True}


class NamespaceLabelHandler:
    """The /v1/admitlabel logic (namespacelabel.go:63-87): only exempt
    namespaces may carry the ignore label."""

    def __init__(self, exempt_namespaces: tuple = ()):
        self.exempt = set(exempt_namespaces)

    def handle(self, admission_review: dict) -> dict:
        request = admission_review.get("request") or {}
        uid = request.get("uid") or ""
        obj = request.get("object") or {}
        name = (obj.get("metadata") or {}).get("name") or request.get("name")
        labels = (obj.get("metadata") or {}).get("labels") or {}
        allowed = True
        reason = ""
        if IGNORE_LABEL in labels and name not in self.exempt:
            allowed = False
            reason = (f"Only exempt namespaces may have the {IGNORE_LABEL} "
                      "label")
        response: dict[str, Any] = {"uid": uid, "allowed": allowed}
        if not allowed:
            response["status"] = {"code": 403, "reason": reason}
        return _envelope(admission_review, response)


class MutationHandler:
    """The /v1/mutate logic (reference pkg/webhook/mutation.go),
    transport-independent.

    Rides the same MicroBatcher as validation: handler threads enqueue
    gk-reviews; the flusher ships the whole batch through
    MutationSystem.mutate_batch, which computes applicability for the
    entire micro-batch in ONE vectorized matcher sweep (the same
    signature-grouped path the validation mask uses) and then applies
    each review's matched mutators on the host, pass after pass, to
    convergence. The response is an RFC-6902 JSONPatch (base64, as the
    API server expects) or a plain allow when nothing changed."""

    def __init__(self, system, kube=None,
                 batcher: Optional[MicroBatcher] = None,
                 fail_closed: bool = False,
                 batch_max_wait: float = 0.005,
                 max_queue: int = 0,
                 default_timeout: float = DEFAULT_WEBHOOK_TIMEOUT_S):
        self.system = system
        self.kube = kube
        self.batcher = batcher or MicroBatcher(
            None, max_wait=batch_max_wait, evaluate=self._evaluate_batch,
            max_queue=max_queue, plane="mutation")
        self.fail_closed = fail_closed
        self.default_timeout = default_timeout

    def _lookup_namespace(self, name: str):
        if self.kube is None:
            return None
        try:
            return self.kube.get(("", "v1", "Namespace"), name)
        except NotFound:
            return None

    def _evaluate_batch(self, reviews: list[dict]) -> list:
        return self.system.mutate_batch(reviews, self._lookup_namespace)

    def handle(self, admission_review: dict,
               deadline: Optional[float] = None,
               trace=gtrace.NOOP) -> dict:
        t0 = time.monotonic()
        request = admission_review.get("request") or {}
        uid = request.get("uid") or ""
        if deadline is None:
            deadline = request_deadline(request, self.default_timeout)
        status = "allow"
        try:
            response = self._decide(request, deadline, trace=trace)
        except AdmissionShed as e:
            status = "shed"
            response = {"allowed": not self.fail_closed,
                        "status": {"code": 429, "message": str(e)}}
        except AdmissionDeadline as e:
            status = "timeout"
            response = {"allowed": not self.fail_closed,
                        "status": {"code": 504, "message": str(e)}}
        except Exception as e:
            log.error("mutation error", details=str(e))
            status = "error"
            response = {"allowed": not self.fail_closed,
                        "status": {"code": 500, "message": str(e)}}
        metrics.report_mutation_request(status, time.monotonic() - t0)
        trace.set_status(status)
        response["uid"] = uid
        return _envelope(admission_review, response)

    def _decide(self, request: dict,
                deadline: Optional[float] = None,
                trace=gtrace.NOOP) -> dict:
        username = (request.get("userInfo") or {}).get("username")
        if username == SERVICE_ACCOUNT:
            return {"allowed": True}
        kind = request.get("kind") or {}
        if (kind.get("group") or "") in (TEMPLATE_GROUP, CONSTRAINT_GROUP,
                                         "mutations.gatekeeper.sh"):
            # gatekeeper's own resources are never mutated
            return {"allowed": True}
        obj = request.get("object")
        if not isinstance(obj, dict):
            return {"allowed": True}  # DELETE / subresource: nothing to patch
        if not self.system.active():
            # empty (or fully quarantined) mutator library: don't pay the
            # micro-batch wait — the MWC matches the whole cluster, so
            # this is the hot path until mutators are installed
            return {"allowed": True}
        # no per-request namespace prefetch: the batched matcher resolves
        # namespaces through _lookup_namespace only for mutators whose
        # match actually needs them (once per projection group, not per
        # request)
        mutated = self.batcher.submit(dict(request), deadline=deadline,
                                      trace=trace)
        if mutated is None:
            return {"allowed": True}
        from ..mutation.patch import json_patch

        patch = json_patch(obj, mutated)
        if not patch:
            return {"allowed": True}
        return {
            "allowed": True,
            "patchType": "JSONPatch",
            "patch": base64.b64encode(
                json.dumps(patch).encode()).decode(),
        }


# -------------------------------------------------- fast HTTP transport

_HTTP_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                 405: "Method Not Allowed", 500: "Internal Server Error",
                 503: "Service Unavailable"}


class FastHTTPServer:
    """Minimal threaded HTTP/1.1 POST server for the admission hot path.

    `BaseHTTPRequestHandler` costs ~1ms per request at webhook payload
    sizes (email-module header parsing, per-header writes, logging
    plumbing) — more than the whole admission decision. This hand-rolled
    loop parses the request line + the three headers that matter
    (Content-Length / Transfer-Encoding / Connection, plus a 100-
    continue Expect), reads the body, and answers with ONE sendall.
    Keep-alive by default (HTTP/1.1 semantics; Connection: close and
    HTTP/1.0 honored), TLS via the wrapped listening socket, an idle
    timeout so silent clients cannot pin threads forever, and in-flight
    accounting for the graceful-shutdown drain.

    `dispatch(path, body) -> (status, payload_bytes)` is the entire
    application surface."""

    def __init__(self, addr: tuple, dispatch, reuse_port: bool = False,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None,
                 idle_timeout: float = 60.0):
        import socket as _socket
        import socketserver

        outer = self
        self.dispatch = dispatch
        self.idle_timeout = idle_timeout
        self._inflight = 0
        self._inflight_lock = threading.Lock()

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True
            request_queue_size = 128

            def server_bind(self):
                if reuse_port:
                    self.socket.setsockopt(_socket.SOL_SOCKET,
                                           _socket.SO_REUSEPORT, 1)
                super().server_bind()

            def finish_request(self, request, client_address):
                outer._serve_connection(request)

            def handle_error(self, request, client_address):
                # keep-alive clients dropping a connection mid-request
                # (reset, broken pipe, idle timeout, TLS teardown) are
                # routine — one log line, not a traceback
                import sys as _sys
                exc = _sys.exc_info()[1]
                if isinstance(exc, (ConnectionError, TimeoutError,
                                    OSError, ssl.SSLError)):
                    log.info("client connection dropped",
                             details=str(exc))
                    return
                super().handle_error(request, client_address)

        self.server = _Server(addr, None)
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self.server.socket = ctx.wrap_socket(self.server.socket,
                                                 server_side=True)
        self.port = self.server.server_address[1]

    # one thread per connection; requests loop here until close
    def _serve_connection(self, conn) -> None:
        import socket as _socket

        try:
            conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except OSError:
            pass
        conn.settimeout(self.idle_timeout)
        rfile = conn.makefile("rb", 65536)
        try:
            while True:
                line = rfile.readline(65537)
                if not line:
                    return
                if line in (b"\r\n", b"\n"):
                    continue  # stray CRLF between pipelined requests
                try:
                    method, path, version = line.split(None, 2)
                except ValueError:
                    self._respond(conn, 400, b"", close=True)
                    return
                close_after = not version.strip().endswith(b"1.1")
                clen = 0
                chunked = False
                traceparent = None
                while True:
                    h = rfile.readline(65537)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    key, _, value = h.partition(b":")
                    key = key.strip().lower()
                    value = value.strip()
                    if key == b"content-length":
                        try:
                            clen = int(value)
                        except ValueError:
                            clen = 0
                    elif key == b"transfer-encoding":
                        chunked = b"chunked" in value.lower()
                    elif key == b"traceparent":
                        # the one tracing header that matters: a W3C
                        # span context from the caller joins our trace
                        traceparent = value.decode("latin-1")
                    elif key == b"connection":
                        v = value.lower()
                        if b"close" in v:
                            close_after = True
                        elif b"keep-alive" in v:
                            close_after = False
                    elif key == b"expect" and \
                            value.lower().startswith(b"100-"):
                        conn.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
                body = (self._read_chunked(rfile) if chunked
                        else (rfile.read(clen) if clen > 0 else b""))
                if method != b"POST":
                    self._respond(conn, 405, b"", close_after)
                    if close_after:
                        return
                    continue
                # in-flight accounting for the graceful-shutdown drain:
                # idle keep-alive connections do NOT count (the thread
                # parks on readline between requests)
                with self._inflight_lock:
                    self._inflight += 1
                extra_headers = None
                try:
                    out = self.dispatch(path.decode("latin-1"), body,
                                        traceparent)
                    # dispatch returns (status, payload) or (status,
                    # payload, extra_headers) — the tracing path adds
                    # X-Trace-Id without taxing the untraced one
                    if len(out) == 3:
                        status, payload, extra_headers = out
                    else:
                        status, payload = out
                except Exception as e:  # a dispatch bug must still
                    # ANSWER (zero unanswered admissions), not drop the
                    # socket and leave the API server to its timeout
                    log.error("dispatch error", details=str(e))
                    status, payload = 500, b""
                finally:
                    with self._inflight_lock:
                        self._inflight -= 1
                self._respond(conn, status, payload, close_after,
                              extra_headers)
                if close_after:
                    return
        except (ConnectionError, TimeoutError, OSError, ssl.SSLError):
            return  # routine client teardown
        finally:
            try:
                rfile.close()
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _read_chunked(rfile) -> bytes:
        # minimal RFC 7230 §4.1 decoder (the API server normally sends
        # Content-Length; this keeps chunked senders working)
        out = bytearray()
        while True:
            size_line = rfile.readline(65537)
            if not size_line:
                raise ConnectionError("EOF inside chunked body")
            size = int(size_line.split(b";", 1)[0].strip() or b"0", 16)
            if size == 0:
                while rfile.readline(65537) not in (b"\r\n", b"\n", b""):
                    pass  # trailers
                return bytes(out)
            out += rfile.read(size)
            rfile.readline(65537)  # chunk-terminating CRLF

    @staticmethod
    def _respond(conn, status: int, payload: bytes,
                 close: bool = False,
                 extra_headers: Optional[dict] = None) -> None:
        extra = ""
        if extra_headers:
            extra = "".join(f"{k}: {v}\r\n"
                            for k, v in extra_headers.items())
        head = ("HTTP/1.1 %d %s\r\n"
                "Content-Type: application/json\r\n"
                "Content-Length: %d\r\n%s%s\r\n"
                % (status, _HTTP_REASONS.get(status, "OK"), len(payload),
                   extra, "Connection: close\r\n" if close else ""))
        release = getattr(payload, "release", None)
        if release is None:
            conn.sendall(head.encode("ascii") + payload)
            return
        # reply-ring payload (control/shm.RingSlice): vectored write
        # straight from the shared segment, then release the slot back
        # to the engine's allocator — even when the client vanished
        try:
            mv = payload.mv
            try:
                # ssl.SSLSocket raises NotImplementedError (not
                # AttributeError) for sendmsg — TLS copies into its
                # encryption buffer anyway, so concat there
                sent = conn.sendmsg((head.encode("ascii"), mv))
            except (AttributeError, NotImplementedError):
                conn.sendall(head.encode("ascii") + bytes(mv))
                return
            total = len(head) + len(mv)
            if sent < total:
                conn.sendall(
                    memoryview(head.encode("ascii") + bytes(mv))[sent:])
        finally:
            release()

    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    # socketserver API passthrough (bench/tests drive these directly)
    def serve_forever(self) -> None:
        self.server.serve_forever()

    def shutdown(self) -> None:
        self.server.shutdown()

    def server_close(self) -> None:
        self.server.server_close()


class WebhookServer:
    """HTTPS transport over the handlers (FastHTTPServer dispatch)."""

    def __init__(self, validation: Optional[ValidationHandler],
                 ns_label: Optional[NamespaceLabelHandler],
                 port: int = 8443, certfile: Optional[str] = None,
                 keyfile: Optional[str] = None, addr: str = "",
                 reuse_port: bool = False,
                 mutation: Optional[MutationHandler] = None,
                 preview=None):
        """reuse_port: bind with SO_REUSEPORT so multiple serving
        PROCESSES share one port (the kernel load-balances accepts) —
        the single-process Python frontend is GIL-bound, and this is
        how one node runs N webhook workers without a proxy.

        `preview` (a control.preview.PreviewEngine) serves the what-if
        /v1/preview endpoint when given."""
        self.validation = validation
        self.ns_label = ns_label
        self.mutation = mutation
        self.preview = preview
        self.http = FastHTTPServer((addr, port), self._dispatch,
                                   reuse_port=reuse_port,
                                   certfile=certfile, keyfile=keyfile)
        self.server = self.http.server  # legacy handle (bench/tests)
        self.port = self.http.port
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="webhook", daemon=True)

    def _dispatch(self, path: str, body: bytes,
                  traceparent: Optional[str] = None) -> tuple:
        tr = gtrace.TRACER.start(gtrace.ADMISSION, traceparent)
        t_parse0 = time.monotonic() if tr.sampled else 0.0
        try:
            review = jsonio.loads(body)
        except ValueError:
            tr.set_status("bad_request")
            tr.finish()
            return 400, b""
        if tr.sampled:
            tr.add_span("frontend_parse", t_parse0, time.monotonic())
        # admission.k8s.io/v1 carries NO timeoutSeconds in the request
        # body — a real API server conveys its webhook timeout only as
        # the ?timeout=5s URL query. Fold it into the request so
        # deadline propagation sees the REAL budget (a body field, e.g.
        # from tests or direct callers, wins)
        request = (review or {}).get("request") \
            if isinstance(review, dict) else None
        if isinstance(request, dict) and "timeoutSeconds" not in request:
            t = parse_timeout_query(path.partition("?")[2])
            if t is not None:
                request["timeoutSeconds"] = t
        # un-served endpoints 404 (an operation not requested must not
        # answer admission decisions for it)
        route = route_path(path)
        # the trace kwarg rides only on sampled requests: unsampled
        # calls stay signature-identical for handler stubs/embedders
        kw = {"trace": tr} if tr.sampled else {}
        if route == "preview" and self.preview is not None:
            # not an AdmissionReview: the preview engine answers its own
            # JSON (it may run for seconds — per-connection handler
            # threads mean admission requests are not behind it)
            status, payload = self.preview.handle_http(body)
            tr.set_status("preview")
            tr.finish()
            return status, payload
        if route == "admitlabel" and self.ns_label is not None:
            out = self.ns_label.handle(review)
        elif route == "admit" and self.validation is not None:
            out = self.validation.handle(review, **kw)
        elif route == "mutate" and self.mutation is not None:
            out = self.mutation.handle(review, **kw)
        else:
            tr.set_status("not_found")
            tr.finish()
            return 404, b""
        if not tr.sampled:
            return 200, encode_envelope(out)
        with tr.span("serialize"):
            payload = encode_envelope(out)
        tr.finish()
        return 200, payload, {"X-Trace-Id": tr.trace_id}

    def start(self) -> None:
        self._thread.start()

    def stop(self, drain_timeout: float = 10.0) -> None:
        """Graceful shutdown: stop accepting, let in-flight reviews
        finish (their batcher flushes answer them per the failure
        stance), then tear the pipeline down — SIGTERM must not drop
        sockets mid-review."""
        self.server.shutdown()  # stop the accept loop; handlers continue
        end = time.monotonic() + drain_timeout
        while time.monotonic() < end:
            if self.http.inflight() == 0:
                break
            time.sleep(0.02)
        for handler in (self.validation, self.mutation):
            if handler is not None:
                handler.batcher.drain(
                    max(0.5, end - time.monotonic()))
                handler.batcher.stop()
        self.server.server_close()
