"""Control-plane utilities.

Counterparts of the reference pkg/util: GVK-packed reconcile requests
(pack.go:16-57), enforcement-action validation (enforcement_action.go:11-45),
pod identity (pod_info.go), and byPod HA status helpers (ha_status.go:14-50,
util/constraint/unstructured_ha_status.go:19-133).
"""

from __future__ import annotations

import os
from typing import Optional

VALID_ENFORCEMENT_ACTIONS = ("deny", "dryrun", "warn")
DEFAULT_ENFORCEMENT_ACTION = "deny"


class UnrecognizedEnforcementAction(Exception):
    pass


def validate_enforcement_action(action: str) -> None:
    if action not in VALID_ENFORCEMENT_ACTIONS:
        raise UnrecognizedEnforcementAction(
            f"Invalid enforcement action {action!r}; must be one of "
            f"{VALID_ENFORCEMENT_ACTIONS}"
        )


# ------------------------------------------------------- packed GVK requests


def pack_request(gvk: tuple, name: str, namespace: str = "") -> str:
    """Encode GVK + object identity into one watch-event request token
    (the reference packs GVK into reconcile request names, pack.go)."""
    group, version, kind = gvk
    ns_part = f"{namespace}/" if namespace else ""
    return f"{group}|{version}|{kind}|{ns_part}{name}"


def unpack_request(token: str) -> tuple[tuple, str, str]:
    group, version, kind, rest = token.split("|", 3)
    if "/" in rest:
        namespace, name = rest.split("/", 1)
    else:
        namespace, name = "", rest
    return (group, version, kind), name, namespace


# ---------------------------------------------------------------- pod info

# explicit identity overrides (--pod-name/--pod-namespace flags): byPod
# statuses must carry the STABLE downward-API pod identity, not whatever
# hostname the process happens to see — a replaced pod then overwrites
# its own status slot instead of accumulating one per restart
_POD_NAME_OVERRIDE: Optional[str] = None
_POD_NAMESPACE_OVERRIDE: Optional[str] = None


def set_pod_identity(name: Optional[str] = None,
                     namespace: Optional[str] = None) -> None:
    global _POD_NAME_OVERRIDE, _POD_NAMESPACE_OVERRIDE
    if name:
        _POD_NAME_OVERRIDE = name
    if namespace:
        _POD_NAMESPACE_OVERRIDE = namespace


def pod_name() -> str:
    if _POD_NAME_OVERRIDE:
        return _POD_NAME_OVERRIDE
    return os.environ.get("POD_NAME", os.environ.get("HOSTNAME", "gatekeeper"))


def pod_namespace() -> str:
    if _POD_NAMESPACE_OVERRIDE:
        return _POD_NAMESPACE_OVERRIDE
    return os.environ.get("POD_NAMESPACE", "gatekeeper-system")


# ------------------------------------------------------------ byPod status


def get_by_pod_status(obj: dict) -> Optional[dict]:
    """This pod's entry in status.byPod (HA: each replica owns one slot)."""
    status = obj.get("status") or {}
    for entry in status.get("byPod") or []:
        if isinstance(entry, dict) and entry.get("id") == pod_name():
            return entry
    return None


def set_by_pod_status(obj: dict, entry: dict) -> None:
    """Upsert this pod's status entry, preserving other pods' entries."""
    entry = dict(entry)
    entry["id"] = pod_name()
    status = obj.setdefault("status", {})
    by_pod = [e for e in status.get("byPod") or []
              if not (isinstance(e, dict) and e.get("id") == pod_name())]
    by_pod.append(entry)
    by_pod.sort(key=lambda e: e.get("id") or "")
    status["byPod"] = by_pod


def delete_by_pod_status(obj: dict) -> None:
    status = obj.get("status") or {}
    by_pod = [e for e in status.get("byPod") or []
              if not (isinstance(e, dict) and e.get("id") == pod_name())]
    status["byPod"] = by_pod


def prune_stale_by_pod(obj: dict, live_ids: set) -> bool:
    """Drop byPod entries whose pod id is not in `live_ids` (pods that
    no longer exist — their statuses must be garbage-collected, not
    accumulate forever as replicas churn). Returns True when any entry
    was pruned (the caller must write the status back)."""
    status = obj.get("status") or {}
    by_pod = status.get("byPod") or []
    kept = [e for e in by_pod
            if not isinstance(e, dict) or e.get("id") in live_ids]
    if len(kept) == len(by_pod):
        return False
    obj.setdefault("status", {})["byPod"] = kept
    return True


def by_pod_status_unchanged(obj: dict, entry: dict) -> bool:
    """True when this pod's existing byPod entry already equals `entry`
    (ignoring the id field) — lets controllers skip no-op status writes
    that would loop MODIFIED events back into their own queues."""
    cur = get_by_pod_status(obj)
    return cur is not None and {**cur, "id": None} == {**entry, "id": None}
