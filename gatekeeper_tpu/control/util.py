"""Control-plane utilities.

Counterparts of the reference pkg/util: GVK-packed reconcile requests
(pack.go:16-57), enforcement-action validation (enforcement_action.go:11-45),
pod identity (pod_info.go), and byPod HA status helpers (ha_status.go:14-50,
util/constraint/unstructured_ha_status.go:19-133).
"""

from __future__ import annotations

import os
from typing import Optional

VALID_ENFORCEMENT_ACTIONS = ("deny", "dryrun")
DEFAULT_ENFORCEMENT_ACTION = "deny"


class UnrecognizedEnforcementAction(Exception):
    pass


def validate_enforcement_action(action: str) -> None:
    if action not in VALID_ENFORCEMENT_ACTIONS:
        raise UnrecognizedEnforcementAction(
            f"Invalid enforcement action {action!r}; must be one of "
            f"{VALID_ENFORCEMENT_ACTIONS}"
        )


# ------------------------------------------------------- packed GVK requests


def pack_request(gvk: tuple, name: str, namespace: str = "") -> str:
    """Encode GVK + object identity into one watch-event request token
    (the reference packs GVK into reconcile request names, pack.go)."""
    group, version, kind = gvk
    ns_part = f"{namespace}/" if namespace else ""
    return f"{group}|{version}|{kind}|{ns_part}{name}"


def unpack_request(token: str) -> tuple[tuple, str, str]:
    group, version, kind, rest = token.split("|", 3)
    if "/" in rest:
        namespace, name = rest.split("/", 1)
    else:
        namespace, name = "", rest
    return (group, version, kind), name, namespace


# ---------------------------------------------------------------- pod info


def pod_name() -> str:
    return os.environ.get("POD_NAME", os.environ.get("HOSTNAME", "gatekeeper"))


def pod_namespace() -> str:
    return os.environ.get("POD_NAMESPACE", "gatekeeper-system")


# ------------------------------------------------------------ byPod status


def get_by_pod_status(obj: dict) -> Optional[dict]:
    """This pod's entry in status.byPod (HA: each replica owns one slot)."""
    status = obj.get("status") or {}
    for entry in status.get("byPod") or []:
        if isinstance(entry, dict) and entry.get("id") == pod_name():
            return entry
    return None


def set_by_pod_status(obj: dict, entry: dict) -> None:
    """Upsert this pod's status entry, preserving other pods' entries."""
    entry = dict(entry)
    entry["id"] = pod_name()
    status = obj.setdefault("status", {})
    by_pod = [e for e in status.get("byPod") or []
              if not (isinstance(e, dict) and e.get("id") == pod_name())]
    by_pod.append(entry)
    by_pod.sort(key=lambda e: e.get("id") or "")
    status["byPod"] = by_pod


def delete_by_pod_status(obj: dict) -> None:
    status = obj.get("status") or {}
    by_pod = [e for e in status.get("byPod") or []
              if not (isinstance(e, dict) and e.get("id") == pod_name())]
    status["byPod"] = by_pod


def by_pod_status_unchanged(obj: dict, entry: dict) -> bool:
    """True when this pod's existing byPod entry already equals `entry`
    (ignoring the id field) — lets controllers skip no-op status writes
    that would loop MODIFIED events back into their own queues."""
    cur = get_by_pod_status(obj)
    return cur is not None and {**cur, "id": None} == {**entry, "id": None}
