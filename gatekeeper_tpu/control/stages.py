"""Central registry of trace-stage and sweep-phase names.

Every name passed to a span recorder (``Trace.span``/``add_span``/
``add_phase``, the frontends' ``observe_stage``/``stage_hook``, the
driver's ``PhaseTimers`` phases) must be declared here — the
``gatekeeper_tpu_stage_duration_seconds{stage}`` label set is BOUNDED
by this table, dashboards join against it, and the README stage table
renders from it (``python -m tools.gklint --stages-md``; the
``tests/test_gklint.py`` sync test keeps README honest).

The gklint ``jit-stage`` checker enforces membership statically: a
stage literal not in this table fails CI, so a typo'd span name can't
mint an unbounded metric series or a dashboard hole.

This module must stay dependency-free (no jax, no package siblings):
the linter loads it by file path, outside the package import graph.
"""

from __future__ import annotations

# name -> (plane hint, one-line description). The plane hint is
# documentation only — report_stage labels the plane at runtime.
STAGES: dict[str, tuple[str, str]] = {
    # admission plane ------------------------------------------------
    "frontend_parse": (
        "admission", "HTTP read + JSON parse on the frontend process"),
    "backplane_forward": (
        "admission", "one-way hop: frontend enqueue to engine frame "
        "receipt over the backplane socket"),
    "ring_write": (
        "admission", "frontend copy of the review into its shm "
        "request ring"),
    "ring_read": (
        "admission", "engine-side zero-copy JSON decode off the "
        "mapped request ring"),
    "engine_queue": (
        "admission", "frame receipt to evaluation-pool pickup inside "
        "the engine"),
    "batch_seal": (
        "admission", "micro-batch collection window: submit to "
        "evaluation start"),
    "evaluate": (
        "both", "batched driver evaluation (admission) or the audit "
        "sweep's aggregate evaluation wall"),
    "cache_hit": (
        "admission", "decision-cache lookup that answered the request"),
    "serialize": (
        "admission", "AdmissionReview response envelope encoding"),
    "respond": (
        "admission", "verdict bytes written back over the backplane"),
    # audit plane ----------------------------------------------------
    "list_delta_apply": (
        "audit", "inventory list / watch-delta application ahead of "
        "the sweep"),
    "encode": (
        "audit", "review encoding into the dense feature tensors"),
    "delta_serve": (
        "audit", "incremental encoded-row cache serve (dirty-row "
        "re-encode)"),
    "device_sweep": (
        "audit", "XLA sweep dispatch + device wait"),
    "materialize": (
        "audit", "violation message materialization from firing "
        "(row, constraint) pairs"),
    "interp_eval": (
        "audit", "interpreter-path evaluation (kinds without device "
        "programs)"),
    "compile": (
        "audit", "XLA program acquisition (AOT deserialize or "
        "lower+compile)"),
    "evaluate_other": (
        "audit", "evaluation wall not covered by an instrumented "
        "phase"),
    "status_write": (
        "audit", "streamed per-kind constraint-status write (writer "
        "thread, overlaps the sweep)"),
    "status_writes": (
        "audit", "post-sweep constraint-status write pass"),
    "status_write_stream": (
        "audit", "streamed status-write wall attributed to the sweep "
        "that overlapped it"),
    "shard_sweeps": (
        "audit", "sharded plane: per-shard slice sweep dispatch + "
        "composition into one audit round (leader side)"),
    # fleet-scan plane -----------------------------------------------
    "scan_load": (
        "scan", "fleet scan: feeder wait on the loader-process queue "
        "(parse + envelope synthesis off the hot path)"),
    "scan_dedupe": (
        "scan", "fleet scan: content-hash dedupe pass over one loader "
        "chunk"),
    "scan_feed": (
        "scan", "fleet scan: bulk-batch round trip — begin to verdict "
        "receipt on the wire tier"),
    "scan_report": (
        "scan", "fleet scan: verdict rejoin + streaming JSONL record "
        "emission for one bulk batch"),
}

STAGE_NAMES = frozenset(STAGES)


def stages_markdown() -> str:
    """The README stage table, rendered from this registry."""
    out = ["| stage | plane | what it measures |",
           "| --- | --- | --- |"]
    for name in sorted(STAGES):
        plane, desc = STAGES[name]
        out.append(f"| `{name}` | {plane} | {desc} |")
    return "\n".join(out)
