"""Request-scoped tracing, latency attribution, and a flight recorder.

PR 5 split admission across four processes and five queues; a p99
number alone cannot say WHERE the time went. The reference line answers
with pprof through controller-runtime — the TPU-native analog here is a
zero-dependency span layer:

  * W3C `traceparent` is accepted at the HTTP edge and the trace id is
    echoed back as `X-Trace-Id`, so a trace started by the API server
    (or curl) joins ours;
  * a compact span context rides the backplane Q frames and is pinned
    to each MicroBatcher entry, so one admission decision decomposes
    into named stages (frontend_parse -> backplane_forward ->
    engine_queue -> batch_seal -> evaluate / cache_hit -> serialize ->
    respond) and one audit sweep decomposes into its phases
    (list_delta_apply -> encode -> device_sweep -> materialize ->
    status_writes);
  * completed traces feed three sinks: per-stage latency histograms
    (`gatekeeper_tpu_stage_duration_seconds{plane,stage}`), a bounded
    in-memory FLIGHT RECORDER that always retains the N slowest and N
    most recent complete traces per plane (dumped by /debug/traces),
    and structured slow-request log lines past --trace-slow-threshold.

Sampling is stride-based and the unsampled hot path pays near zero: a
preallocated no-op context is returned without allocating a single
span object (tests assert this via the module allocation counter).
Shed / timeout / fail-open decisions still produce (truncated) spans,
so a storm is diagnosable after the fact from the recorder alone.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from collections import deque
from typing import Optional

from .logging import logger

log = logger("trace")

# planes a trace can belong to (label value on the stage histograms)
ADMISSION = "admission"
AUDIT = "audit"

# allocation counter: bumped by every real Trace/Span construction so a
# test can assert the unsampled hot path allocates NO span objects
ALLOCATIONS = 0


def new_trace_id() -> str:
    """128-bit random trace id, lowercase hex (W3C trace-id format)."""
    return os.urandom(16).hex()


def parse_traceparent(header: Optional[str]) -> tuple[Optional[str], bool]:
    """(trace_id, sampled) from a W3C `traceparent` header value:
    `00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>`. Malformed
    or all-zero ids return (None, False) — never raise on wire input."""
    if not header:
        return None, False
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None, False
    version, trace_id, parent_id, flags = parts[0], parts[1], parts[2], \
        parts[3]
    if len(version) != 2 or len(trace_id) != 32 or len(parent_id) != 16:
        return None, False
    trace_id = trace_id.lower()
    # STRICT hex digits only: int(x, 16) also accepts '0x', '_', sign,
    # and whitespace — ids that would later blow up bytes.fromhex when
    # the context rides the backplane frame
    if not _HEX_DIGITS.issuperset(trace_id):
        return None, False
    try:
        sampled = bool(int(flags[:2], 16) & 0x01)
    except ValueError:
        return None, False
    if trace_id == "0" * 32:
        return None, False
    return trace_id, sampled


_HEX_DIGITS = frozenset("0123456789abcdef")


def format_traceparent(trace_id: str, span_id: str = "",
                       sampled: bool = True) -> str:
    return "00-%s-%s-%s" % (trace_id, (span_id or os.urandom(8).hex()),
                            "01" if sampled else "00")


class Span:
    """One named stage of a trace. `t0`/`t1` are time.monotonic()
    instants (CLOCK_MONOTONIC is system-wide on Linux, so spans stamped
    in the frontend processes compare directly against engine spans).
    `remote` marks spans timed by ANOTHER process whose aggregated
    duration already ships separately (the frontends' S-frame stage
    deltas) — the metrics sink skips them to avoid double counting."""

    __slots__ = ("name", "t0", "t1", "remote")

    def __init__(self, name: str, t0: float, t1: float,
                 remote: bool = False):
        global ALLOCATIONS
        ALLOCATIONS += 1
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.remote = remote

    @property
    def duration(self) -> float:
        return max(0.0, self.t1 - self.t0)


class Trace:
    """One request's (or one audit sweep's) span collection. Not
    thread-safe per span — each stage is recorded by the one thread
    that ran it; finish() is called exactly once."""

    __slots__ = ("trace_id", "plane", "t0", "t1", "spans", "status",
                 "attrs", "_tracer", "_finished")

    sampled = True

    def __init__(self, tracer: "Tracer", plane: str, trace_id: str):
        global ALLOCATIONS
        ALLOCATIONS += 1
        self._tracer = tracer
        self.plane = plane
        self.trace_id = trace_id
        self.t0 = time.monotonic()
        self.t1 = 0.0
        self.spans: list[Span] = []
        self.status = ""
        self.attrs: dict = {}
        self._finished = False

    # ------------------------------------------------------------ spans

    def add_span(self, name: str, t0: float, t1: float,
                 remote: bool = False) -> None:
        self.spans.append(Span(name, t0, t1, remote=remote))

    def add_phase(self, name: str, seconds: float) -> None:
        """Duration-only span (synthesized from a PhaseTimers diff —
        audit phases overlap under the dispatch pipeline, so only the
        accumulated duration is meaningful, not wall-clock position).
        Anchored after the last recorded span for a readable dump."""
        if seconds < 0:
            seconds = 0.0
        anchor = self.spans[-1].t1 if self.spans else self.t0
        self.spans.append(Span(name, anchor, anchor + seconds))

    def span(self, name: str):
        """Context manager timing one stage:  with tr.span("encode"):"""
        return _SpanCtx(self, name)

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def set_status(self, status: str) -> None:
        """Outcome tag (allow/deny/shed/timeout/error): shed and
        timeout verdicts still finish their (truncated) trace, so a
        storm's flight-recorder dump shows where the budget went."""
        self.status = status

    # ----------------------------------------------------------- finish

    def finish(self) -> None:
        if self._finished:  # double finish (error path raced): first wins
            return
        self._finished = True
        self.t1 = time.monotonic()
        self._tracer._complete(self)

    def duration(self) -> float:
        return max(0.0, (self.t1 or time.monotonic()) - self.t0)

    def to_dict(self) -> dict:
        """Plain-container form for the recorder / JSON dump. Span
        times are RELATIVE to the trace start (monotonic instants mean
        nothing outside the process)."""
        return {
            "trace_id": self.trace_id,
            "plane": self.plane,
            "status": self.status,
            "duration_s": round(self.duration(), 6),
            "attrs": dict(self.attrs),
            "spans": [{"stage": s.name,
                       "start_s": round(s.t0 - self.t0, 6),
                       "duration_s": round(s.duration, 6)}
                      for s in self.spans],
        }


class _SpanCtx:
    __slots__ = ("_trace", "_name", "_t0")

    def __init__(self, trace: Trace, name: str):
        self._trace = trace
        self._name = name

    def __enter__(self):
        self._t0 = time.monotonic()
        return self._trace

    def __exit__(self, *exc):
        # gklint: allow(stage) reason=plumbing; the name was a checked literal at the span() call site
        self._trace.add_span(self._name, self._t0, time.monotonic())
        return False


class _NoopSpanCtx:
    __slots__ = ()

    def __enter__(self):
        return NOOP

    def __exit__(self, *exc):
        return False


_NOOP_SPAN_CTX = _NoopSpanCtx()


class NoopTrace:
    """Preallocated no-op context served to every unsampled request:
    all recorders are empty methods, `sampled` is False, and nothing is
    allocated on the hot path (the module-level singleton is returned
    by reference)."""

    __slots__ = ()

    sampled = False
    trace_id = ""
    plane = ""
    status = ""

    def add_span(self, name, t0, t1, remote=False):
        pass

    def add_phase(self, name, seconds):
        pass

    def span(self, name):
        return _NOOP_SPAN_CTX

    def set_attr(self, key, value):
        pass

    def set_status(self, status):
        pass

    def finish(self):
        pass

    def duration(self):
        return 0.0

    def to_dict(self):
        return {}


NOOP = NoopTrace()


class FlightRecorder:
    """Bounded in-memory trace retention, per plane: the N most RECENT
    complete traces (a ring) and the N SLOWEST (a min-heap keyed on
    duration, so the cheapest of the slow set is evicted first). Holds
    plain dicts, never live objects — a dumped trace cannot pin request
    bodies or device buffers in memory."""

    def __init__(self, keep: int = 32):
        self.keep = keep
        self._lock = threading.Lock()
        self._recent: dict[str, deque] = {}
        self._slow: dict[str, list] = {}  # plane -> [(dur, seq, dict)]
        self._seq = 0

    def record(self, trace: Trace) -> None:
        entry = trace.to_dict()
        dur = entry["duration_s"]
        with self._lock:
            self._seq += 1
            recent = self._recent.get(trace.plane)
            if recent is None:
                recent = self._recent[trace.plane] = deque(maxlen=self.keep)
            recent.append(entry)
            slow = self._slow.setdefault(trace.plane, [])
            if len(slow) < self.keep:
                heapq.heappush(slow, (dur, self._seq, entry))
            elif slow and dur > slow[0][0]:
                heapq.heapreplace(slow, (dur, self._seq, entry))

    def dump(self) -> dict:
        """JSON-ready dump for /debug/traces: per plane, the recent
        ring (oldest first) and the slow set (slowest first)."""
        with self._lock:
            planes = {}
            for plane in sorted(set(self._recent) | set(self._slow)):
                slow = sorted(self._slow.get(plane, []),
                              key=lambda e: (-e[0], e[1]))
                planes[plane] = {
                    "recent": list(self._recent.get(plane, ())),
                    "slowest": [e[2] for e in slow],
                }
            return {"planes": planes}

    def clear(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slow.clear()


class Tracer:
    """Sampling decisions + completed-trace sinks.

    Stride sampling (1 of every round(1/rate)) instead of an RNG call:
    deterministic, testable, and the unsampled path costs one integer
    compare. An inbound `traceparent` with the sampled flag FORCES
    sampling — a caller who started a distributed trace gets our spans
    regardless of the local rate."""

    def __init__(self, sample_rate: float = 0.0,
                 slow_threshold_s: float = 1.0,
                 recorder: Optional[FlightRecorder] = None,
                 metrics_sink: bool = True):
        self.recorder = recorder or FlightRecorder()
        self.metrics_sink = metrics_sink
        self.slow_threshold_s = slow_threshold_s
        self._n = 0
        self.configure(sample_rate, slow_threshold_s)

    def configure(self, sample_rate: float,
                  slow_threshold_s: Optional[float] = None) -> None:
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self._stride = (0 if self.sample_rate <= 0.0
                        else max(1, round(1.0 / self.sample_rate)))
        if slow_threshold_s is not None:
            self.slow_threshold_s = float(slow_threshold_s)

    # ----------------------------------------------------------- starts

    def start(self, plane: str, traceparent: Optional[str] = None,
              force: bool = False):
        """A Trace when this request samples, else the preallocated
        NOOP singleton (zero allocation)."""
        trace_id = None
        if traceparent is not None:
            trace_id, inbound_sampled = parse_traceparent(traceparent)
            force = force or (trace_id is not None and inbound_sampled)
        if not force:
            if not self._stride:
                return NOOP
            # benign data race under the GIL: a dropped increment skews
            # the effective rate immeasurably and costs no lock
            self._n += 1
            if self._n % self._stride:
                return NOOP
        return Trace(self, plane, trace_id or new_trace_id())

    def resume(self, plane: str, trace_id: str) -> Trace:
        """Engine-side continuation of a span context carried over the
        backplane: the frontend already made the sampling decision."""
        return Trace(self, plane, trace_id)

    def sample_context(self, traceparent: Optional[str] = None
                       ) -> Optional[str]:
        """Edge-side sampling WITHOUT allocating a trace: the trace id
        (hex) when this request samples, else None. The frontends use
        this — they forward the span context over the backplane and
        never own a recorder, so a full Trace object would be waste."""
        trace_id = None
        force = False
        if traceparent is not None:
            trace_id, force = parse_traceparent(traceparent)
        if not force:
            if not self._stride:
                return None
            self._n += 1
            if self._n % self._stride:
                return None
        return trace_id or new_trace_id()

    # ------------------------------------------------------------ sinks

    def _complete(self, trace: Trace) -> None:
        if self.metrics_sink:
            try:
                from . import metrics
                metrics.report_trace(trace.plane)
                for s in trace.spans:
                    if not s.remote:
                        # the trace id rides along as the histogram
                        # bucket's OpenMetrics exemplar: a slow p99
                        # bucket links straight to this trace's
                        # /debug/traces flight-recorder entry
                        # gklint: allow(stage) reason=sink plumbing; every span name was a checked literal where recorded
                        metrics.report_stage(trace.plane, s.name,
                                             s.duration,
                                             trace_id=trace.trace_id)
            except Exception:  # the sink must never fail a request
                pass
        try:
            self.recorder.record(trace)
        except Exception:
            pass
        # the slow log is a REQUEST sink: audit sweeps are force-traced
        # and routinely run past any request-scale threshold — every
        # sweep already logs its duration and phase stats on the
        # `audit complete` line, so slow-warning them here would spam
        # a warning per interval forever and bury real anomalies
        if self.slow_threshold_s > 0 and trace.plane != AUDIT and \
                trace.duration() >= self.slow_threshold_s:
            try:
                log.warning("slow request trace",
                            event_type="slow_trace", **trace.to_dict())
            except Exception:
                pass


# process-global tracer: main.py configures it from --trace-sample-rate
# / --trace-slow-threshold; frontends configure their own in
# frontend_main. Rate 0 = tracing off (every start() returns NOOP)
# until configured.
TRACER = Tracer(sample_rate=0.0)
