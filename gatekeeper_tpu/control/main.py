"""Process entrypoint.

Counterpart of the reference main.go:99-252: flag surface, logging setup,
client construction over the TPU driver, controller/watch/webhook/audit/
upgrade/metrics wiring, graceful teardown. The same process serves either
or both operations (--operation webhook / audit; both when unset,
main.go:114-118).

Run:  python -m gatekeeper_tpu.control.main --operation audit ...
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from ..client import Backend
from ..ir import TpuDriver
from ..target import K8sValidationTarget
from . import chaos as chaos_debug
from . import health
from . import logging as glog
from . import metrics
from . import trace as gtrace
from .audit import (
    DEFAULT_AUDIT_INTERVAL,
    DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT,
    DEFAULT_FULL_RESYNC_EVERY,
    AuditManager,
)
from ..utils.faults import FAULTS
from .certs import CertRotator
from .controllers import ControllerManager
from .kube import FakeKube, RestKubeClient
from .resilience import CircuitBreaker, GuardedKube, RetryBudget
from .upgrade import UpgradeManager
from .webhook import (
    MicroBatcher,
    MutationHandler,
    NamespaceLabelHandler,
    ValidationHandler,
    WebhookServer,
)

log = glog.logger("main")


def _parse_fail_closed(value: str) -> bool:
    """--fail-closed value parser: booleans or the webhook
    failurePolicy spellings, so deploy templating can feed the one
    failurePolicy value to both the API object and this process."""
    v = str(value).strip().lower()
    if v in ("true", "1", "yes", "fail"):
        return True
    if v in ("false", "0", "no", "ignore"):
        return False
    raise argparse.ArgumentTypeError(
        f"cannot parse {value!r}; use true/false or Fail/Ignore")


def _parse_bool(value: str) -> bool:
    """Plain boolean flag values (chart templating renders YAML bools
    as True/False; accept every common spelling)."""
    v = str(value).strip().lower()
    if v in ("true", "1", "yes", "on"):
        return True
    if v in ("false", "0", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"cannot parse {value!r} as a bool")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gatekeeper-tpu",
        description="TPU-native Kubernetes admission/audit policy engine",
    )
    # flag parity with the reference (SURVEY.md §5 config/flag system)
    p.add_argument("--operation", action="append", default=None,
                   choices=["webhook", "audit", "mutation-webhook"],
                   help="operations to run; repeatable; webhook+audit "
                        "when unset (mutation-webhook must be requested "
                        "explicitly)")
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--cert-dir", default="/certs")
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--metrics-backend", default="prometheus")
    p.add_argument("--prometheus-port", type=int, default=8888)
    p.add_argument("--health-addr", default=":9090")
    p.add_argument("--audit-interval", type=float,
                   default=DEFAULT_AUDIT_INTERVAL)
    p.add_argument("--constraint-violations-limit", type=int,
                   default=DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT)
    p.add_argument("--audit-from-cache", default="false")
    p.add_argument("--audit-incremental", default="false",
                   help="maintain a persistent watch-fed encoded "
                        "inventory and audit only the delta each sweep "
                        "(steady-state sweeps patch dirty rows instead "
                        "of re-encoding the cluster)")
    p.add_argument("--audit-full-resync-every", type=int,
                   default=DEFAULT_FULL_RESYNC_EVERY,
                   help="with --audit-incremental: every Nth sweep "
                        "re-lists and re-encodes the whole inventory "
                        "from scratch (self-healing backstop); 0 "
                        "disables the periodic re-encode (the first "
                        "sweep still encodes from scratch)")
    p.add_argument("--audit-shards", type=int, default=1,
                   help="partition the audit inventory across N audit "
                        "engine processes by consistent hash of (GVK, "
                        "namespace). Each shard owns its slice end to "
                        "end — encoded feature rows, delta cache, "
                        "incremental sweep state — in its own process "
                        "pinned to its own device; the leader tracks "
                        "per-slice watches, broadcasts join-relevant "
                        "columns, and composes per-shard sweeps into "
                        "one bit-equal audit round. 1 = unsharded")
    p.add_argument("--stream-audit", nargs="?", const=True,
                   default=False, type=_parse_bool,
                   help="with --audit-incremental: evaluate dirty rows "
                        "AS WATCH EVENTS ARRIVE (micro-batched by "
                        "--stream-window-ms) and publish changed "
                        "constraint statuses immediately — violation "
                        "detection latency (event -> status, the "
                        "gatekeeper_tpu_violation_detection_seconds "
                        "histogram) drops from up to a full "
                        "--audit-interval to milliseconds. The interval "
                        "sweep is demoted to a reconciliation backstop "
                        "that reports any drift it had to repair "
                        "(gatekeeper_tpu_audit_backstop_drift_total)")
    p.add_argument("--stream-window-ms", type=float, default=25.0,
                   help="streaming-audit debounce: after the first "
                        "buffered watch event, wait this long for the "
                        "burst to coalesce before flushing (a full "
                        "--stream-max-batch flushes early)")
    p.add_argument("--stream-max-batch", type=int, default=512,
                   help="streaming-audit early-flush threshold: pending "
                        "dirty events at or beyond this count flush "
                        "without waiting out the window")
    p.add_argument("--preview-endpoint", nargs="?", const=True,
                   default=True, type=_parse_bool,
                   help="serve POST /v1/preview (what-if evaluation of "
                        "a candidate ConstraintTemplate/Constraint over "
                        "the full cached inventory, without enforcing "
                        "it) on the webhook port; see also "
                        "--preview-port for audit-only pods")
    p.add_argument("--preview-port", type=int, default=0,
                   help="ALSO serve /v1/preview on this dedicated "
                        "plaintext port (audit pods have no webhook "
                        "port but own the freshest inventory); 0 "
                        "disables the dedicated listener")
    p.add_argument("--log-denies", action="store_true")
    p.add_argument("--fail-closed", nargs="?", const=True, default=False,
                   type=_parse_fail_closed,
                   help="internal webhook errors DENY instead of the "
                        "default fail-open allow (match this to the "
                        "deployed failurePolicy); errored decisions are "
                        "reported as status=error either way. Bare flag "
                        "or a value: true/false or the failurePolicy "
                        "spelling Fail/Ignore. Applies to every webhook "
                        "unless --mutation-fail-closed overrides the "
                        "mutating one")
    p.add_argument("--mutation-fail-closed", nargs="?", const=True,
                   default=None, type=_parse_fail_closed,
                   help="failure stance of the MUTATING webhook only "
                        "(same value forms as --fail-closed; defaults "
                        "to --fail-closed when unset). The chart "
                        "templates --mutation-fail-closed="
                        "{{ .Values.mutations.failurePolicy }} so the "
                        "MutatingWebhookConfiguration and the process "
                        "flip together without touching the validating "
                        "webhook's stance")
    p.add_argument("--mutation-max-iterations", type=int, default=10,
                   help="convergence pass budget for the mutating "
                        "webhook; a review whose matched mutators still "
                        "change the object after N full passes errors "
                        "instead of admitting a half-mutated object")
    p.add_argument("--mutation-batch-max-wait", type=float, default=0.005,
                   help="mutating webhook micro-batch collection window "
                        "(seconds)")
    p.add_argument("--admission-max-queue", type=int, default=4096,
                   help="micro-batch queue depth beyond which admission "
                        "requests are SHED immediately with the failure-"
                        "stance verdict (status=shed) instead of "
                        "queueing into certain timeout; 0 = unbounded. "
                        "With --admission-workers > 1 the bound lives on "
                        "the ENGINE side of the backplane, so it stays "
                        "global across all frontends")
    p.add_argument("--admission-workers", type=int, default=1,
                   help="pre-forked HTTP frontend processes over the "
                        "shared batching backplane: each binds the "
                        "webhook port with SO_REUSEPORT and does accept/"
                        "TLS/parse only, forwarding reviews (with their "
                        "deadlines) over a Unix socket to THIS process — "
                        "the one engine owning JAX and the micro-"
                        "batcher, so requests from all workers coalesce "
                        "into shared device micro-batches. 1 = serve "
                        "HTTP in-process (no backplane)")
    p.add_argument("--backplane-socket", default="",
                   help="Unix socket path for the frontend<->engine "
                        "backplane (default: a per-process path under "
                        "the system temp dir); only used with "
                        "--admission-workers > 1 or --admission-engines "
                        "> 1 (engine k > 0 listens on <socket>.<k>)")
    p.add_argument("--admission-engines", type=int, default=1,
                   help="admission ENGINE processes, one per chip: this "
                        "process stays engine 0; engines 1..N-1 are "
                        "spawned children (gatekeeper_tpu.control."
                        "engine), each pinning jax.devices()[k] and "
                        "owning its own Client/MicroBatcher behind its "
                        "own backplane socket. Frontends route reviews "
                        "by least-load (request-hash fallback) across "
                        "all engines and fail over mid-burst when one "
                        "dies, so admission_rps scales with chips. "
                        "Library mutations fan out to every engine "
                        "(each bumps its own decision-cache generation); "
                        "--admission-max-queue is divided across "
                        "engines so the shed bound stays global. "
                        "0 = one engine per visible device. Values > 1 "
                        "imply the backplane even with "
                        "--admission-workers 1")
    p.add_argument("--admission-shm-ring-mb", type=float, default=8.0,
                   help="shared-memory ring size (MB) per admission "
                        "frontend: review bytes ride a /dev/shm ring "
                        "and the backplane socket carries (offset, "
                        "length) descriptors only — zero payload "
                        "copies across the backplane on the happy "
                        "path, with automatic inline-frame fallback "
                        "when a burst outruns the engine. 0 disables "
                        "the rings (inline payload frames as before)")
    p.add_argument("--ingest-grpc", action="store_true",
                   help="serve the bulk gRPC/HTTP2 streaming ingest "
                        "endpoint (gatekeeper.v1.Policy ReviewStream/"
                        "ReviewBatch, evaluation-only surface) on "
                        "--ingest-port: CI scanners and service-mesh "
                        "authorizers pipeline pre-batched reviews "
                        "straight into the micro-batcher, skipping "
                        "HTTP/1.1 framing entirely")
    p.add_argument("--ingest-port", type=int, default=50061,
                   help="port for the --ingest-grpc streaming ingest "
                        "listener")
    p.add_argument("--admission-decision-cache", type=int, default=4096,
                   help="entries in the generation-keyed admission "
                        "decision cache (identical retries and object "
                        "storms skip evaluation; any template/"
                        "constraint/synced-data change invalidates via "
                        "the library generation). 0 disables")
    p.add_argument("--admission-default-timeout", type=float, default=10.0,
                   help="deadline (seconds) assumed for AdmissionReviews "
                        "that carry no request.timeoutSeconds; the "
                        "verdict ships before this minus a safety "
                        "margin, matching the API server's 10s webhook "
                        "default")
    p.add_argument("--kube-breaker-threshold", type=int, default=5,
                   help="consecutive kube WRITE failures that open the "
                        "shared circuit breaker (status writes defer, "
                        "readiness reports the open breaker)")
    p.add_argument("--kube-breaker-reset", type=float, default=30.0,
                   help="seconds an open kube-write breaker waits "
                        "before half-opening for a probe write")
    p.add_argument("--kube-retry-budget", type=float, default=10.0,
                   help="shared token budget for kube write RETRIES "
                        "(first attempts are free); refills at 1/s — "
                        "bounds retry amplification during API-server "
                        "outages")
    p.add_argument("--fault-injection", default="",
                   help="arm chaos faults, e.g. "
                        "'kube.write:error:503@0.5,webhook.flush:sleep:2'"
                        " (see gatekeeper_tpu/utils/faults.py; also via "
                        "GATEKEEPER_TPU_FAULTS)")
    p.add_argument("--state-dir", default="",
                   help="directory for durable state snapshots (the "
                        "warm-restart path: encoded inventory + watch-"
                        "resume resourceVersions, template/constraint/"
                        "mutator library, strtab vocab). Empty disables "
                        "snapshotting; a corrupt or stale snapshot "
                        "falls back to the cold start path, never a "
                        "crash loop")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent XLA compilation cache directory "
                        "(equivalent to GATEKEEPER_TPU_COMPILE_CACHE; an "
                        "explicit JAX_COMPILATION_CACHE_DIR env var still "
                        "wins). Point it at a volume so restarts skip "
                        "XLA compiler time; pair with --state-dir for "
                        "the full AOT deserialize-and-go warm boot")
    p.add_argument("--aot-dir", default="",
                   help="AOT serialized-program store directory "
                        "(ir/aot.py): compiled device executables are "
                        "persisted here and warm boots deserialize them "
                        "instead of recompiling. Defaults to "
                        "<state-dir>/aot when --state-dir is set; empty "
                        "with no --state-dir disables the store (the "
                        "compile cache above still applies)")
    p.add_argument("--snapshot-interval", type=float, default=60.0,
                   help="seconds between periodic state snapshots "
                        "(also taken on SIGTERM drain; SIGHUP forces "
                        "one immediately); <= 0 disables the periodic "
                        "loop")
    p.add_argument("--leader-elect", nargs="?", const=True, default=False,
                   type=_parse_bool,
                   help="coordination.k8s.io/v1 Lease-based leader "
                        "election: only the lease holder runs the audit "
                        "sweep and controller/cert status writers, so "
                        "the deployment scales to replicas > 1 (every "
                        "replica still serves admission)")
    p.add_argument("--leader-lease-duration", type=float, default=15.0,
                   help="leader lease duration (seconds); failover after "
                        "a leader crash completes within one duration "
                        "(graceful shutdown releases the lease "
                        "immediately)")
    p.add_argument("--pod-name", default="",
                   help="stable pod identity for byPod statuses and the "
                        "leader lease (wire the downward-API "
                        "metadata.name here; falls back to $POD_NAME / "
                        "$HOSTNAME)")
    p.add_argument("--pod-namespace", default="",
                   help="namespace for the leader lease and status "
                        "bookkeeping (downward-API metadata.namespace; "
                        "falls back to $POD_NAMESPACE)")
    p.add_argument("--trace-sample-rate", type=float, default=0.01,
                   help="fraction of admission requests traced end to "
                        "end (stride-sampled; near-zero hot-path cost "
                        "when unsampled). Sampled requests answer "
                        "X-Trace-Id, decompose into per-stage spans in "
                        "gatekeeper_tpu_stage_duration_seconds, and "
                        "land in the /debug/traces flight recorder. An "
                        "inbound W3C traceparent with the sampled flag "
                        "always traces. Audit sweeps are always traced. "
                        "0 disables admission tracing")
    p.add_argument("--trace-slow-threshold", type=float, default=1.0,
                   help="seconds beyond which a completed trace also "
                        "logs a structured slow-request line with its "
                        "full stage decomposition; <= 0 disables")
    p.add_argument("--slo-admission-p99", type=float, default=0.1,
                   help="admission-latency SLO threshold (seconds): the "
                        "objective promises 99%% of admission decisions "
                        "complete under this, compiled against the "
                        "request_duration_seconds histogram into the "
                        "gatekeeper_tpu_slo_burn_rate{slo=\"admission_"
                        "p99_latency\"} gauges (5m/1h windows). Should "
                        "be one of the histogram's bucket bounds")
    p.add_argument("--slo-availability-target", type=float, default=0.999,
                   help="admission availability SLO target: at most "
                        "1-target of requests may end shed/timeout/"
                        "error (reads request_count). Burn rate 1.0 = "
                        "consuming the error budget exactly at the "
                        "sustained-compliance rate")
    p.add_argument("--slo-detection-p99", type=float, default=1.0,
                   help="violation-detection SLO threshold (seconds): "
                        "99%% of streaming-audit detections (watch "
                        "event -> status write) must complete under "
                        "this (reads gatekeeper_tpu_violation_"
                        "detection_seconds). Should be one of that "
                        "histogram's bucket bounds")
    p.add_argument("--slo-sample-interval", type=float, default=15.0,
                   help="seconds between SLO totals samples (the ring "
                        "spans the longest burn window at this "
                        "cadence); <= 0 disables the SLO engine, "
                        "burn-rate gauges, and /debug/slo")
    p.add_argument("--adaptive-control", nargs="?", const=True,
                   default=False, type=_parse_bool,
                   help="arm the closed-loop serving controller "
                        "(control/adaptive.py): samples burn rates, "
                        "seal mix/fill, queue depth, duty cycle and "
                        "actuates batch max_wait/max_batch, shed "
                        "depth, engine fan-out, and AOT pre-warm "
                        "inside declared bounds with hysteresis + "
                        "per-knob cooldowns, plus the degradation "
                        "ladder (normal -> tighten_shed -> cache_only "
                        "-> fail_stance). false (the default) is the "
                        "kill switch: knobs hold the configured "
                        "baselines bit-exactly")
    p.add_argument("--adaptive-interval", type=float, default=1.0,
                   help="seconds between adaptive-controller ticks")
    p.add_argument("--adaptive-hysteresis", type=float, default=10.0,
                   help="minimum seconds before a knob may reverse "
                        "direction (the anti-oscillation window); "
                        "same-direction steps wait out the per-knob "
                        "cooldown instead")
    p.add_argument("--debug-endpoints", nargs="?", const=True,
                   default=True, type=_parse_bool,
                   help="serve /debug/traces (flight-recorder dump), "
                        "/debug/templates (per-template compile state, "
                        "quarantine, eval counts), and /debug/profile"
                        "?seconds=N (arm a jax.profiler device-trace "
                        "window) on the metrics and health ports")
    p.add_argument("--disable-cert-rotation", action="store_true")
    p.add_argument("--disable-enforcementaction-validation",
                   action="store_true")
    p.add_argument("--exempt-namespace", action="append", default=[])
    p.add_argument("--webhook-reuse-port", action="store_true",
                   help="bind the webhook port with SO_REUSEPORT so "
                        "multiple worker processes share it (the kernel "
                        "load-balances accepts; one GIL-bound Python "
                        "frontend per worker)")
    p.add_argument("--fake-kube", action="store_true",
                   help="in-memory cluster (development/testing)")
    return p


class Runtime:
    """Everything main() builds, exposed for tests and embedding."""

    def __init__(self, args, kube=None):
        self.args = args
        operations = set(args.operation or ["webhook", "audit"])
        self.operations = operations
        # stable pod identity (downward API via flags): byPod statuses
        # and the leader lease must survive pod replacement under the
        # SAME id, so a restarted pod overwrites its own slot
        from .util import pod_namespace, set_pod_identity
        set_pod_identity(getattr(args, "pod_name", ""),
                         getattr(args, "pod_namespace", ""))
        self.kube = kube if kube is not None else (
            FakeKube() if args.fake_kube else RestKubeClient())
        if isinstance(self.kube, FakeKube):
            self._register_builtin_kinds()
        if getattr(args, "fault_injection", ""):
            FAULTS.configure(args.fault_injection)
            log.warning("fault injection armed",
                        details={"points": FAULTS.armed()})
        # request tracing: the process-global tracer feeds the stage
        # histograms, the flight recorder (/debug/traces), and the
        # slow-request log. With --admission-workers > 1 the FRONTENDS
        # are the sampling edge (the rate rides their spawn args); this
        # engine-side tracer still samples the in-process server and
        # records every audit sweep.
        gtrace.TRACER.configure(
            getattr(args, "trace_sample_rate", 0.01),
            getattr(args, "trace_slow_threshold", 1.0))
        # SLO layer: declarative objectives compiled against the
        # existing request/detection series into 5m/1h burn-rate
        # gauges + /debug/slo (control/slo.py). Sample interval <= 0
        # disables the whole layer.
        self.slo = None
        slo_interval = getattr(args, "slo_sample_interval", 15.0) or 0
        if slo_interval > 0:
            from .slo import SloEngine, default_objectives
            try:
                self.slo = SloEngine(
                    default_objectives(
                        admission_p99_s=getattr(
                            args, "slo_admission_p99", 0.1),
                        availability_target=getattr(
                            args, "slo_availability_target", 0.999),
                        detection_p99_s=getattr(
                            args, "slo_detection_p99", 1.0)),
                    sample_interval_s=slo_interval)
            except ValueError as e:
                # a nonsense target (e.g. 1.0) disables the layer
                # loudly instead of crash-looping the pod
                log.warning("SLO objectives invalid; SLO layer "
                            "disabled", details=str(e))
        # a debug profile window must not run twice concurrently
        self._profile_until = 0.0
        self._profile_lock = threading.Lock()
        # HA: Lease-based leader election — only the lease holder runs
        # the audit sweep and the in-cluster status/CRD/cert writers;
        # every replica serves admission. The elector itself talks to
        # the RAW client (its lease writes must not be fenced by the
        # leadership gate they implement)
        self.elector = None
        if getattr(args, "leader_elect", False):
            from .kube import LeaseElector
            # one lease PER DEPLOYMENT (operation set), not one global:
            # the audit and webhook deployments both elect, and a
            # webhook pod holding a shared lease would starve the audit
            # sweep forever (its own audit loop does not exist)
            lease_name = ("gatekeeper-tpu-leader-"
                          + "-".join(sorted(operations)))
            self.elector = LeaseElector(
                self.kube, lease_name=lease_name,
                namespace=pod_namespace(),
                lease_duration=getattr(args, "leader_lease_duration",
                                       15.0))
        # shared write-resilience: one breaker + retry budget for every
        # control-loop writer (audit status PATCHes, cert secret/CA
        # injection); readiness surfaces the open breaker
        self.write_breaker = CircuitBreaker(
            "kube-writes",
            failure_threshold=getattr(args, "kube_breaker_threshold", 5),
            reset_timeout=getattr(args, "kube_breaker_reset", 30.0))
        budget = RetryBudget(getattr(args, "kube_retry_budget", 10.0))
        self.kube_guard = GuardedKube(self.kube, self.write_breaker,
                                      budget)
        # leadership-fenced guard for the audit + controller writers: a
        # deposed leader's in-flight status writes abort at the proxy
        # (resilience.NotLeader) instead of racing the new leader. With
        # election off it IS the plain guard.
        self.kube_gated = self.kube_guard
        if self.elector is not None:
            self.kube_gated = GuardedKube(
                self.kube, self.write_breaker, budget,
                write_gate=lambda: self.elector.is_leader)
        # cold-start elimination: the compile-cache flag feeds
        # enable_compile_cache (driver construction) through the env
        # hook, and the AOT serialized-program store colocates with the
        # state snapshots (<state-dir>/aot) so ONE volume carries the
        # whole deserialize-and-go warm boot
        import os as _os
        cc_dir = getattr(args, "compile_cache_dir", "") or ""
        if cc_dir:
            _os.environ["GATEKEEPER_TPU_COMPILE_CACHE"] = cc_dir
        aot_dir = getattr(args, "aot_dir", "") or ""
        state_dir = getattr(args, "state_dir", "") or ""
        if not aot_dir and state_dir:
            aot_dir = _os.path.join(state_dir, "aot")
        driver = TpuDriver(aot_dir=aot_dir or None)
        self.opa = Backend(driver).new_client([K8sValidationTarget()])
        self.mutation_system = None
        if "mutation-webhook" in operations:
            from ..mutation import MutationSystem
            self.mutation_system = MutationSystem(
                max_iterations=getattr(args, "mutation_max_iterations", 10))
        # controllers ride the guarded client too: byPod status writes
        # and CRD applies share the one breaker/retry discipline (reads
        # and watches pass straight through the proxy). Deliberately
        # UNGATED even under leader election: byPod slots are keyed by
        # pod id (only the owning pod can write its slot — e.g. a
        # follower surfacing its own device-eval quarantine), and CRD/
        # finalizer applies are idempotent with conflict retries, so
        # fencing them would suppress per-pod state for no safety gain.
        self.manager = ControllerManager(
            self.kube_guard, self.opa,
            validate_actions=not args.disable_enforcementaction_validation,
            mutation_system=self.mutation_system)
        # the driver's device-eval quarantine surfaces on the owning
        # template's byPod status through the template controller
        if hasattr(driver, "on_quarantine"):
            driver.on_quarantine = self.manager.template_ctrl.note_quarantine
        self.audit = None
        self.audit_shards = None   # sharded plane: shard-process supervisor
        self._shard_plane = None
        if "audit" in operations:
            shards = max(1, int(getattr(args, "audit_shards", 1) or 1))
            if shards > 1:
                # sharded inventory plane: N audit engine children, each
                # owning a consistent-hash slice of the inventory; this
                # process stays the leader (watches, routing, status
                # writes, composition)
                from .audit import ShardedAuditPlane
                from .backplane import (
                    AuditShardSupervisor,
                    default_socket_path,
                )

                asock = (getattr(args, "backplane_socket", "")
                         or default_socket_path()) + ".audit"
                shard_spawn = ["--log-level",
                               getattr(args, "log_level", "INFO")]
                if getattr(args, "fault_injection", ""):
                    shard_spawn += ["--fault-injection",
                                    args.fault_injection]
                self.audit_shards = AuditShardSupervisor(
                    shards,
                    socket_for=lambda k, s=asock: f"{s}.{k}",
                    spawn_args=shard_spawn,
                    snapshot_provider=self._audit_shard_snapshot)
                self._shard_plane = ShardedAuditPlane(
                    self.kube_gated, self.opa, self.audit_shards,
                    shards)
            # the guarded client: status writes ride the shared breaker/
            # retry budget; reads and the tracker's watches pass through.
            # Under leader election only the lease holder sweeps.
            self.audit = AuditManager(
                self.kube_gated, self.opa, interval=args.audit_interval,
                constraint_violations_limit=args.constraint_violations_limit,
                audit_from_cache=str(args.audit_from_cache).lower() == "true",
                incremental=str(getattr(args, "audit_incremental",
                                        "false")).lower() == "true",
                full_resync_every=getattr(args, "audit_full_resync_every",
                                          DEFAULT_FULL_RESYNC_EVERY),
                write_breaker=self.write_breaker,
                leader_check=(None if self.elector is None
                              else lambda: self.elector.is_leader),
                stream_audit=getattr(args, "stream_audit", False),
                stream_window_s=getattr(args, "stream_window_ms",
                                        25.0) / 1000.0,
                stream_max_batch=getattr(args, "stream_max_batch", 512),
                shard_plane=self._shard_plane)
        # what-if preview (POST /v1/preview + the dedicated
        # --preview-port listener): candidate templates/constraints
        # evaluated over this process's cached inventory, compiled
        # out-of-band under alias kinds so the serving library is
        # untouched
        self.preview_engine = None
        self.preview_server = None
        if getattr(args, "preview_endpoint", True):
            from .preview import PreviewEngine
            self.preview_engine = PreviewEngine(self.opa)
        self.webhook = None
        self.cert_rotator = None
        # serving plane (--admission-workers > 1): pre-forked HTTP
        # frontends over the shared batching backplane; this process is
        # the engine
        self.backplane = None
        self.frontends = None
        self.engines = None  # N-engine plane: supervisor of engines 1..N-1
        self.validation_handler = None
        self.mutation_handler = None
        if "webhook" in operations or "mutation-webhook" in operations:
            fail_closed = getattr(args, "fail_closed", False)
            validation = ns_label = None
            max_queue = getattr(args, "admission_max_queue", 4096)
            default_timeout = getattr(args, "admission_default_timeout",
                                      10.0)
            if "webhook" in operations:
                # a mutation-only process must NOT serve /v1/admit — a
                # leftover VWC would get decisions from an operation the
                # operator turned off (unserved endpoints 404)
                batcher = MicroBatcher(self.opa, max_queue=max_queue)
                validation = ValidationHandler(
                    self.opa, kube=self.kube, batcher=batcher,
                    log_denies=args.log_denies,
                    validate_enforcement=not
                    args.disable_enforcementaction_validation,
                    traces_provider=lambda:
                    self.manager.config_ctrl.traces,
                    fail_closed=fail_closed,
                    default_timeout=default_timeout,
                    decision_cache_size=getattr(
                        args, "admission_decision_cache", 4096))
                ns_label = NamespaceLabelHandler(
                    tuple(args.exempt_namespace))
            mutation = None
            mut_fail_closed = getattr(args, "mutation_fail_closed", None)
            if self.mutation_system is not None:
                mutation = MutationHandler(
                    self.mutation_system, kube=self.kube,
                    fail_closed=fail_closed if mut_fail_closed is None
                    else mut_fail_closed,
                    batch_max_wait=getattr(args, "mutation_batch_max_wait",
                                           0.005),
                    max_queue=max_queue,
                    default_timeout=default_timeout)
            self.validation_handler = validation
            self.mutation_handler = mutation
            certfile = keyfile = None
            if not args.disable_cert_rotation:
                # guarded: secret persistence and CA-bundle injection
                # retry under the shared breaker/budget
                self.cert_rotator = CertRotator(self.kube_guard,
                                                args.cert_dir)
                try:
                    self.cert_rotator.refresh_certs()
                    certfile = f"{args.cert_dir}/tls.crt"
                    keyfile = f"{args.cert_dir}/tls.key"
                except Exception as e:
                    log.warning("cert bootstrap failed; serving plaintext",
                                details=str(e))
            workers = getattr(args, "admission_workers", 1) or 1
            engines = getattr(args, "admission_engines", 1)
            if engines == 0:
                # auto: one engine per visible chip
                try:
                    import jax
                    engines = max(1, len(jax.devices()))
                except Exception:
                    engines = 1
            if engines > 1 or workers > 1:
                from .backplane import (
                    BackplaneEngine,
                    EngineSupervisor,
                    FrontendSupervisor,
                    default_socket_path,
                )

                sock = getattr(args, "backplane_socket", "") \
                    or default_socket_path()
                serve = []
                if validation is not None:
                    serve += ["admit", "admitlabel"]
                if mutation is not None:
                    serve += ["mutate"]
                if self.preview_engine is not None:
                    # frontends forward /v1/preview over the backplane;
                    # the router pins it to engine 0 (this process — the
                    # one whose tracker feeds the live inventory)
                    serve += ["preview"]
                # N-engine plane: this process is engine 0; engines
                # 1..N-1 are child processes, each pinned to its own
                # chip with its own Client/MicroBatcher/socket. The
                # queue bound is divided so it stays GLOBAL: N engines
                # each bounding max_queue/N in-flight admissions.
                if engines > 1:
                    try:
                        import jax
                        n_dev = len(jax.devices())
                    except Exception:
                        n_dev = 0
                    if n_dev and engines > n_dev:
                        # device pinning wraps modulo the device count:
                        # over-provisioned engines time-share chips,
                        # which degrades instead of scales — say so
                        log.warning(
                            "--admission-engines exceeds visible "
                            "devices; engines will time-share chips",
                            details={"engines": engines,
                                     "devices": n_dev})
                    share = max(1, max_queue // engines) if max_queue \
                        else 0
                    for handler in (validation, mutation):
                        if handler is not None:
                            handler.batcher.max_queue = share
                    metrics.set_engine_id("0")
                    spawn_args = ["--serve", ",".join(serve),
                                  "--admission-max-queue", str(share),
                                  "--admission-default-timeout",
                                  str(default_timeout),
                                  "--admission-decision-cache",
                                  str(getattr(args,
                                              "admission_decision_cache",
                                              4096)),
                                  "--log-level",
                                  getattr(args, "log_level", "INFO"),
                                  "--trace-sample-rate",
                                  str(getattr(args, "trace_sample_rate",
                                              0.01)),
                                  "--trace-slow-threshold",
                                  str(getattr(args,
                                              "trace_slow_threshold",
                                              1.0))]
                    if args.log_denies:
                        spawn_args += ["--log-denies"]
                    if fail_closed:
                        spawn_args += ["--fail-closed"]
                    if mut_fail_closed is not None:
                        spawn_args += ["--mutation-fail-closed",
                                       "true" if mut_fail_closed
                                       else "false"]
                    spawn_args += ["--mutation-max-iterations",
                                   str(getattr(args,
                                               "mutation_max_iterations",
                                               10))]
                    for ns in args.exempt_namespace:
                        spawn_args += ["--exempt-namespace", ns]
                    self.engines = EngineSupervisor(
                        range(1, engines),
                        socket_for=lambda k, s=sock: f"{s}.{k}",
                        spawn_args=spawn_args,
                        snapshot_provider=self._engine_sync_snapshot)
                    # every library mutation the controllers (or tests)
                    # apply through THIS client fans out to the engine
                    # children; each child's Client bumps its own
                    # generation when the op lands, keeping decision-
                    # cache keys coherent per engine
                    self.opa.on_change = \
                        lambda op, obj: self.engines.replicate(op, obj)
                    if self.mutation_system is not None:
                        self.mutation_system.on_change = \
                            lambda op, obj: self.engines.replicate(op,
                                                                   obj)
                self.backplane = BackplaneEngine(
                    sock, validation=validation, ns_label=ns_label,
                    mutation=mutation, default_timeout=default_timeout,
                    engine_id="0", preview=self.preview_engine)
                self.backplane.configured_workers = workers
                self.frontends = FrontendSupervisor(
                    workers,
                    [sock] + [f"{sock}.{k}" for k in range(1, engines)],
                    port=args.port,
                    certfile=certfile, keyfile=keyfile,
                    serve=tuple(serve), fail_closed=fail_closed,
                    mutation_fail_closed=mut_fail_closed,
                    default_timeout=default_timeout,
                    trace_sample_rate=getattr(args, "trace_sample_rate",
                                              0.01),
                    shm_ring_mb=getattr(args, "admission_shm_ring_mb",
                                        8.0))
            else:
                self.webhook = WebhookServer(
                    validation, ns_label, port=args.port,
                    certfile=certfile, keyfile=keyfile,
                    reuse_port=getattr(args, "webhook_reuse_port", False),
                    mutation=mutation, preview=self.preview_engine)
        # bulk gRPC/HTTP2 streaming ingest (--ingest-grpc): the
        # service/ layer's evaluation-only surface over THIS process's
        # client, so streamed batches share the library, caches, and
        # device programs with the admission plane
        self.ingest_server = None
        if getattr(args, "ingest_grpc", False):
            try:
                from ..service import INGEST_METHODS, make_server

                self.ingest_server, ingest_port = make_server(
                    client=self.opa,
                    address="0.0.0.0:%d" % getattr(args, "ingest_port",
                                                   50061),
                    expose=INGEST_METHODS)
                log.info("grpc streaming ingest configured",
                         details={"port": ingest_port})
            except Exception as e:
                # a missing grpcio / occupied port degrades the ingest
                # endpoint, never the admission plane
                log.warning("grpc streaming ingest unavailable",
                            details=str(e))
                self.ingest_server = None
        preview_port = getattr(args, "preview_port", 0) or 0
        if preview_port and self.preview_engine is not None:
            # dedicated plaintext preview listener: audit-only pods
            # have no webhook port but own the freshest tracker-fed
            # inventory — a WebhookServer with only the preview engine
            # attached 404s every admission route
            self.preview_server = WebhookServer(
                None, None, port=preview_port,
                preview=self.preview_engine)
        if self._shard_plane is not None:
            # AFTER the engines block above: attach() chains onto
            # whatever on_change hook is installed (the admission-engine
            # fan-out when --admission-engines > 1), so both planes see
            # every library op
            self._shard_plane.attach()
        # closed-loop adaptive serving controller (--adaptive-control):
        # samples the SLO/saturation signals and steers the declared
        # knobs; the flag defaulting OFF is the kill switch — disarm
        # restores every captured baseline bit-exactly. Built AFTER the
        # engines block so baselines capture the divided queue share.
        self.adaptive = None
        if getattr(args, "adaptive_control", False) \
                and self.validation_handler is not None:
            from .adaptive import AdaptiveController
            self.adaptive = AdaptiveController(
                batcher=self.validation_handler.batcher,
                engines=self.engines,
                slo=self.slo,
                generation=lambda: self.opa.generation,
                prewarm=self._adaptive_prewarm,
                interval=getattr(args, "adaptive_interval", 1.0),
                hysteresis_s=getattr(args, "adaptive_hysteresis",
                                     10.0),
                on_actuate=self._on_adaptive_actuation)
            # the ladder gates the admission pipeline: rung >= 2
            # serves cache hits only, rung >= 3 answers per stance
            self.validation_handler.ladder = self.adaptive.ladder
        self.upgrade = UpgradeManager(self.kube)
        self.metrics_server = None
        self.health = None
        self._ready = False
        # durable state snapshots (--state-dir): restore on boot (cold
        # fallback on any corruption), snapshot periodically / on
        # SIGTERM drain / on SIGHUP
        self.statestore = None
        self.snapshots = None
        self._build_statestore()
        self._restore_state()

    # ---------------------------------------------------- durable state

    def _build_statestore(self) -> None:
        state_dir = getattr(self.args, "state_dir", "") or ""
        if not state_dir:
            return
        from . import statestore as ss
        try:
            self.statestore = ss.StateStore(state_dir)
        except OSError as e:
            log.warning("state dir unusable; snapshots disabled",
                        details={"dir": state_dir, "error": str(e)})
            return
        providers, blobs = self._snapshot_providers()
        self.snapshots = ss.SnapshotManager(
            self.statestore, providers, blob_providers=blobs,
            interval_s=getattr(self.args, "snapshot_interval", 60.0),
            # the inventory payload is plain containers by construction
            # (_deep_plain); marshal loads ~2x faster than pickle and
            # restore latency is the warm boot
            blob_codecs={"inventory": "marshal"})
        if self._shard_plane is not None:
            # observability section: which ring/fleet produced the
            # tracker slices riding the inventory blob. restore_state
            # discards slices saved under a different shard count, so
            # operators can read WHY a warm boot went cold here.
            plane, sup = self._shard_plane, self.audit_shards
            self.snapshots.add_provider(
                "audit_shards",
                lambda: {"shard_count": plane.shard_count,
                         "map_version": plane.map.version,
                         "generations": {str(k): v for k, v
                                         in sup.generation.items()}})

    def _snapshot_providers(self) -> tuple:
        driver = getattr(self.opa, "driver", None)
        providers = {}
        if hasattr(driver, "vocab_snapshot"):
            providers["vocab"] = driver.vocab_snapshot

        def library():
            snap = self.opa.snapshot_library()
            if self.mutation_system is not None:
                snap["mutators"] = self.mutation_system.sources()
            return snap

        providers["library"] = library
        blobs = {}
        if self.audit is not None and (self.audit.incremental
                                       or self._shard_plane is not None):
            # the inventory rides the BLOB (pickle) path: the frozen
            # in-memory tree round-trips without the O(cluster)
            # re-freeze a JSON restore would pay
            def inventory():
                tracker = self.audit.snapshot_state()
                if tracker is None:
                    return None  # no sweep yet: nothing worth saving
                tree = None
                if hasattr(driver, "inventory_snapshot"):
                    tree = driver.inventory_snapshot()
                return {"tree": tree or {}, "tracker": tracker}

            blobs["inventory"] = inventory
            if hasattr(driver, "encoded_rows_snapshot"):
                blobs["rows"] = driver.encoded_rows_snapshot
        return providers, blobs

    def _restore_state(self) -> None:
        if self.statestore is None:
            return
        from .statestore import restore_section
        driver = getattr(self.opa, "driver", None)
        vocab_ok = False
        if hasattr(driver, "vocab_restore"):
            # vocab FIRST: restored encoded rows hold interned ids, and
            # library re-ingestion interns — the append-only table must
            # replay before anything else touches it
            vocab_ok = restore_section(self.statestore, "vocab",
                                       driver.vocab_restore)

        def apply_library(snap):
            out = self.opa.restore_library(snap)
            if self.mutation_system is not None:
                for m in snap.get("mutators") or []:
                    try:
                        self.mutation_system.upsert(m)
                    except Exception:
                        out["errors"] = out.get("errors", 0) + 1
            log.info("library restored", details=out)

        restore_section(self.statestore, "library", apply_library)
        if self.audit is not None and (self.audit.incremental
                                       or self._shard_plane is not None):
            def apply_inventory(snap):
                n = 0
                if hasattr(driver, "inventory_restore"):
                    n = driver.inventory_restore(snap.get("tree") or {})
                self.audit.restore_state(snap.get("tracker") or {})
                log.info("inventory restored; watches resume from "
                         "persisted resourceVersions",
                         details={"objects": n})

            if restore_section(self.statestore, "inventory",
                               apply_inventory, blob=True) and vocab_ok \
                    and hasattr(driver, "encoded_rows_restore"):
                # encoded rows are a first-audit optimization, not a
                # readiness dependency: load them OFF the boot path.
                # The staleness-guard generation is pinned HERE (before
                # the thread starts) so a delta applied while the blob
                # loads invalidates the stash; adoption also requires a
                # cand match, so a racing sweep just re-extracts.
                driver.mark_rows_restore_base()
                threading.Thread(
                    target=lambda: restore_section(
                        self.statestore, "rows",
                        driver.encoded_rows_restore, blob=True),
                    name="rows-restore", daemon=True).start()

    # ------------------------------------------------- N-engine plane

    def _engine_sync_snapshot(self) -> dict:
        """The full-library sync op the EngineSupervisor sends a fresh
        (or healed) engine child: templates/constraints, the synced
        inventory tree, and mutator sources. The child replays it
        through its own Client, so its decision-cache generation
        reflects the library it actually evaluates."""
        snap = {"library": self.opa.snapshot_library()}
        driver = getattr(self.opa, "driver", None)
        if hasattr(driver, "inventory_snapshot"):
            snap["data"] = driver.inventory_snapshot()
        if self.mutation_system is not None:
            snap["mutators"] = self.mutation_system.sources()
        return snap

    def _audit_shard_snapshot(self, k: int) -> dict:
        """The per-shard sync op the AuditShardSupervisor sends a fresh
        (or respawned) shard child: full library + that shard's
        inventory slice rebuilt from the leader's tree (owned objects
        whole, join partners column-pruned). The slice heals without a
        cluster re-list — tracker state never left the leader."""
        return self._shard_plane.sync_snapshot(k)

    # ---------------------------------------------------- debug endpoints

    def debug_providers(self) -> dict:
        """The /debug/* registry mounted on BOTH the metrics and the
        health servers: the flight-recorder dump, the per-template
        compile/quarantine/eval-count state, and the device-profile
        armer."""
        return {
            "traces": lambda q: gtrace.TRACER.recorder.dump(),
            "templates": self._debug_templates,
            "profile": self._debug_profile,
            "slo": lambda q: (self.slo.status() if self.slo is not None
                              else {"disabled": True,
                                    "hint": "--slo-sample-interval > 0 "
                                            "enables the SLO engine"}),
            "adaptive": lambda q: (self.adaptive.status(q)
                                   if self.adaptive is not None
                                   else {"disabled": True,
                                         "hint": "--adaptive-control "
                                                 "arms the controller"}),
            # the chaos ledger: active/last schedule + what fired, plus
            # the fault injector's armed/fired snapshots (answers even
            # with no orchestrator — a GATEKEEPER_TPU_FAULTS game day
            # still shows its armed points here)
            "chaos": chaos_debug.debug_snapshot,
        }

    def _on_adaptive_actuation(self, act) -> None:
        """Controller actuation hook: batcher-knob movements replicate
        to the engine children so the fleet's batch economics stay
        coherent (set_knobs only records the payload — the supervisor's
        monitor loop does the socket work, keeping the control loop
        no-block)."""
        if self.engines is None or self.validation_handler is None:
            return
        if act.knob in ("batch_max_wait", "batch_max_batch",
                        "shed_depth"):
            self.engines.set_knobs(
                self.validation_handler.batcher.knob_values())

    def _adaptive_prewarm(self) -> int:
        """Churn-triggered off-path AOT pre-warm over every known
        template kind (runs on the controller's one-shot thread, never
        on the control loop)."""
        driver = getattr(self.opa, "driver", None)
        if not hasattr(driver, "prewarm_templates"):
            return 0
        return driver.prewarm_templates(self.opa.template_kinds())

    def _debug_templates(self, query: str) -> dict:
        driver = getattr(self.opa, "driver", None)
        if hasattr(driver, "templates_debug"):
            return driver.templates_debug()
        # interpreter-only driver (tests/embedders): still answer with
        # the known template kinds rather than 500
        return {"templates": {k: {"state": "interpreter"}
                              for k in self.opa.template_kinds()}}

    def _debug_profile(self, query: str) -> dict:
        """Arm a jax.profiler device-trace window (?seconds=N, capped):
        the TPU-native pprof analog — the resulting trace directory
        opens in TensorBoard/Perfetto with the device timeline."""
        from urllib.parse import parse_qsl
        seconds = 5.0
        for k, v in parse_qsl(query, keep_blank_values=True):
            if k == "seconds":
                try:
                    seconds = float(v)
                except ValueError:
                    pass
        # capped at 30s: the window thread is deliberately NON-daemon
        # (a daemon profiler thread skips the profiler's thread-state
        # teardown and the interpreter segfaults at exit), so the cap
        # bounds how long an in-flight window can delay process exit —
        # strictly UNDER the manifests' 60s terminationGracePeriodSeconds
        # so a window armed right before pod deletion still leaves the
        # SIGTERM drain room to finish before the kubelet SIGKILLs
        seconds = min(max(seconds, 0.5), 30.0)
        with self._profile_lock:
            now = time.monotonic()
            if now < self._profile_until:
                return {"armed": False,
                        "error": "a profile window is already running",
                        "remaining_s": round(self._profile_until - now,
                                             1)}
            self._profile_until = now + seconds
        import tempfile
        log_dir = tempfile.mkdtemp(prefix="gatekeeper-tpu-trace-")

        def run():
            try:
                from ..utils.profiling import device_trace
                with device_trace(log_dir):
                    time.sleep(seconds)
                log.info("device profile window captured",
                         details={"log_dir": log_dir,
                                  "seconds": seconds})
            except Exception as e:
                log.error("device profile window failed",
                          details=str(e))
            finally:
                with self._profile_lock:
                    self._profile_until = 0.0

        threading.Thread(target=run, name="debug-profile",
                         daemon=False).start()
        return {"armed": True, "seconds": seconds, "log_dir": log_dir,
                "viewer": "tensorboard --logdir <log_dir> (or load the "
                          "trace in Perfetto) for the device timeline"}

    def snapshot_now(self) -> None:
        """Force an immediate snapshot (SIGHUP): runs off-thread, safe
        from a signal context; save_now serializes concurrent passes."""
        if self.snapshots is None:
            return
        threading.Thread(target=self.snapshots.save_now,
                         name="snapshot-now", daemon=True).start()

    def _register_builtin_kinds(self) -> None:
        for gvk, namespaced in [
            (("", "v1", "Namespace"), False),
            (("", "v1", "Pod"), True),
            (("", "v1", "Service"), True),
            (("", "v1", "Secret"), True),
            (("apps", "v1", "Deployment"), True),
            (("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate"),
             False),
            (("config.gatekeeper.sh", "v1alpha1", "Config"), True),
            (("apiextensions.k8s.io", "v1beta1",
              "CustomResourceDefinition"), False),
            (("admissionregistration.k8s.io", "v1beta1",
              "ValidatingWebhookConfiguration"), False),
            (("admissionregistration.k8s.io", "v1beta1",
              "MutatingWebhookConfiguration"), False),
            (("mutations.gatekeeper.sh", "v1alpha1", "Assign"), False),
            (("mutations.gatekeeper.sh", "v1alpha1", "AssignMetadata"),
             False),
            (("mutations.gatekeeper.sh", "v1alpha1", "ModifySet"), False),
            (("coordination.k8s.io", "v1", "Lease"), True),
        ]:
            self.kube.register_kind(gvk, namespaced=namespaced)

    def _register_saturation_probes(self) -> None:
        """Scrape-time gauge refreshers for the capacity-attribution
        read: admission/mutation queue depth (the --admission-max-queue
        counter itself) and the engine's eval duty cycle. The
        backplane engine and the streaming audit register their own
        probes; frontends ship per-worker in-flight over S frames."""
        if self.validation_handler is not None:
            batcher = self.validation_handler.batcher
            metrics.register_saturation_probe(
                "admission-queue",
                lambda: metrics.report_queue_depth(
                    "admission", batcher.pending()))
        if self.mutation_handler is not None:
            mbatcher = self.mutation_handler.batcher
            metrics.register_saturation_probe(
                "mutation-queue",
                lambda: metrics.report_queue_depth(
                    "mutation", mbatcher.pending()))
        driver = getattr(self.opa, "driver", None)
        if hasattr(driver, "duty_cycle"):
            metrics.register_saturation_probe(
                "engine-duty-cycle",
                lambda: metrics.report_duty_cycle(driver.duty_cycle()))

    def start(self) -> None:
        # build identity FIRST: every scrape of this process carries
        # the version/jax/platform/device-count join gauge
        metrics.report_build_info()
        self._register_saturation_probes()
        debug = (self.debug_providers()
                 if getattr(self.args, "debug_endpoints", True) else None)
        if self.args.metrics_backend == "prometheus":
            try:
                self.metrics_server = metrics.serve(
                    self.args.prometheus_port, debug_providers=debug)
            except OSError as e:
                log.warning("metrics port unavailable", details=str(e))
        # healthz/readyz on --health-addr (reference main.go:205-212)
        health_addr = getattr(self.args, "health_addr", "")
        addr = health.parse_addr(health_addr)
        if addr is not None:
            try:
                self.health = health.HealthServer(*addr)
                self.health.add_readiness("runtime", lambda: self._ready)
                if self.webhook is None and self.backplane is None:
                    # audit/controller-only pods surface the open
                    # kube-write breaker through readiness. Webhook
                    # pods must NOT: every replica shares one API
                    # server, so a cluster-wide write brownout would
                    # open every replica's breaker at once and pull
                    # ALL admission endpoints — turning a partial
                    # degradation (serving works, writes don't) into a
                    # full admission outage. There the breaker stays
                    # observable via metrics and logs.
                    self.health.add_readiness(
                        "kube-writes",
                        lambda: not self.write_breaker.is_open)
                if self.webhook:
                    self.health.add_readiness(
                        "webhook",
                        lambda: self.webhook._thread.is_alive())
                if self.backplane is not None:
                    # the engine listener and every pre-forked frontend
                    # must be up for the plane to serve (a crashed
                    # frontend is respawned by the supervisor; readiness
                    # dips meanwhile)
                    self.health.add_readiness("backplane-engine",
                                              self.backplane.alive)
                    self.health.add_readiness("admission-frontends",
                                              self.frontends.alive)
                if self.engines is not None:
                    # DELIBERATELY not all-engines-alive: one dead
                    # engine child is a degraded-but-serving state —
                    # frontends fail its requests over to the survivors
                    # and the supervisor respawns it. Pulling the pod
                    # from the Service for that would turn a partial
                    # capacity dip into a full endpoint outage.
                    # Readiness only requires the supervisor itself to
                    # still be monitoring/respawning.
                    self.health.add_readiness(
                        "engine-supervisor", self.engines.monitoring)
                # liveness watchdogs: a wedged micro-batch pipeline
                # (dead flusher, hung evaluation with a growing queue)
                # fails /healthz so k8s restarts the pod — the
                # handlers exist in both the in-process and the
                # backplane serving modes
                if self.validation_handler is not None:
                    self.health.add_liveness(
                        "admission-batcher",
                        self.validation_handler.batcher.healthy)
                if self.mutation_handler is not None:
                    self.health.add_liveness(
                        "mutation-batcher",
                        self.mutation_handler.batcher.healthy)
                if self.adaptive is not None:
                    # a dead armed control loop means knobs freeze at
                    # whatever the last tick left them — not baselines,
                    # not steered; restart the pod (disarm-on-shutdown
                    # restores the baselines first)
                    self.health.add_liveness("adaptive-controller",
                                             self.adaptive.healthy)
                if self.audit_shards is not None:
                    # same contract as the admission-engine supervisor:
                    # a dead shard mid-respawn is degraded-but-healing;
                    # only a dead MONITOR (nothing left to respawn it)
                    # pulls readiness
                    self.health.add_readiness(
                        "audit-shard-supervisor",
                        self.audit_shards.monitoring)
                if self.audit:
                    self.health.add_liveness("audit-loop",
                                             self.audit.healthy)
                if self.audit and self.statestore is not None:
                    # warm restart: hold readiness until restored state
                    # has been re-validated against a live list (a cold
                    # or non-restored boot passes trivially)
                    self.health.add_readiness("state-restore",
                                              self.audit.restore_ready)
                if self.elector is not None:
                    # a dead elector loop means leadership can silently
                    # never arrive (or never lapse); surface it. NOT
                    # being leader is a healthy state — followers stay
                    # Ready and serve admission.
                    self.health.add_readiness("leader-elector",
                                              self.elector.healthy)
                if debug:
                    # same registry as the metrics server: an audit-only
                    # pod scraped by nothing still dumps its recorder
                    for name, provider in debug.items():
                        self.health.add_debug(name, provider)
                self.health.start()
            except OSError as e:
                log.warning("health port unavailable", details=str(e))
        elif health_addr and health_addr != "0":
            # a typo'd flag silently dropping liveness probes would
            # crash-loop the deployment with no hint in the logs
            log.warning("--health-addr not understood; health endpoints "
                        "disabled", details={"health_addr": health_addr})
        if self.elector is not None:
            self.elector.start()
        self.upgrade.upgrade()
        self.manager.start()
        if self.audit_shards is not None:
            # shard children before the audit loop: the supervisor's
            # first resync fills each slice, and the first sweep's
            # dispatch retries through any shard still syncing
            self.audit_shards.start()
        if self.audit:
            self.audit.start()
        if self.cert_rotator:
            self.cert_rotator.start(watch_manager=self.manager.wm)
        if self.webhook:
            self.webhook.start()
        if self.preview_server is not None:
            self.preview_server.start()
        if self.backplane is not None:
            # engines first: frontends connect eagerly on boot
            self.backplane.start()
            if self.engines is not None:
                self.engines.start()
                metrics.report_admission_engines(
                    1 + len(self.engines.engine_ids),
                    1 + self.engines.alive_count())
            self.frontends.start()
            metrics.report_admission_workers(
                self.backplane.configured_workers,
                self.backplane.connected)
        if self.ingest_server is not None:
            try:
                self.ingest_server.start()
                log.info("grpc streaming ingest serving")
            except Exception as e:
                log.warning("grpc streaming ingest failed to start",
                            details=str(e))
                self.ingest_server = None
        if self.snapshots is not None:
            self.snapshots.start()
        if self.slo is not None:
            self.slo.start()
        if self.adaptive is not None:
            # AFTER slo.start(): the first tick reads a seeded export
            self.adaptive.arm()
        self._ready = True
        # long-lived-server GC tuning: everything built so far (engine,
        # policy caches, codegen closures) is effectively permanent;
        # freezing it out of the collector's scan set keeps multi-ms
        # gen-2 pauses out of the admission tail
        import gc
        gc.collect()
        gc.freeze()
        log.info("gatekeeper-tpu started",
                 details={"operations": sorted(self.operations)})

    def stop(self) -> None:
        self._ready = False
        if self.adaptive is not None:
            # FIRST: no actuation may race the teardown below, and the
            # baseline restore leaves the knobs as configured for any
            # still-serving embedder/test plane
            self.adaptive.disarm()
        if self.slo is not None:
            self.slo.stop()
        for probe in ("admission-queue", "mutation-queue",
                      "engine-duty-cycle"):
            metrics.unregister_saturation_probe(probe)
        # the gauges are SET-only: zero the stopped plane's depths (and
        # its duty cycle) so a still-running process (embedders, tests)
        # doesn't export the last sampled value forever
        if self.validation_handler is not None:
            metrics.report_queue_depth("admission", 0)
        if self.mutation_handler is not None:
            metrics.report_queue_depth("mutation", 0)
        if hasattr(getattr(self.opa, "driver", None), "duty_cycle"):
            metrics.report_duty_cycle(0.0)
        if self.elector is not None:
            # graceful lease release FIRST: the surviving replica takes
            # over immediately instead of waiting out the lease duration
            self.elector.stop()
        if self.webhook:
            self.webhook.stop()
        if self.ingest_server is not None:
            try:
                self.ingest_server.stop(grace=2.0).wait(timeout=10)
            except Exception:
                pass
        if self.preview_server is not None:
            self.preview_server.stop(drain_timeout=1.0)
        if self.backplane is not None:
            # frontends FIRST: each stops accepting and finishes its
            # in-flight HTTP requests (verdicts still flow over the
            # backplane), THEN the engines drain their batchers
            self.frontends.stop()
            if self.engines is not None:
                self.engines.stop()
            self.backplane.stop()
        if self.audit:
            self.audit.stop()
        if self.audit_shards is not None:
            # after the audit loop: no sweep can be dispatched into a
            # stopping fleet
            self.audit_shards.stop()
        if self.snapshots is not None:
            # SIGTERM drain snapshot: the replacement pod warm-boots
            # from state at most seconds old
            self.snapshots.stop()
            try:
                self.snapshots.save_now()
            except Exception as e:
                log.error("drain snapshot failed", details=str(e))
        if self.cert_rotator:
            self.cert_rotator.stop()
        self.manager.stop()
        if self.metrics_server:
            self.metrics_server.shutdown()
        if self.health:
            self.health.shutdown()
        log.info("gatekeeper-tpu stopped")


def warm_cache_main(argv=None) -> int:
    """`gatekeeper-tpu warm-cache`: prepack the compile caches.

    Restores the library/vocab/inventory snapshots from a state dir and
    runs one full audit with INLINE compilation, so every device program
    the restored workload needs lands in the persistent XLA cache and
    the AOT serialized-program store (<state-dir>/aot). Run it at image
    build time or from an initContainer against the state volume: the
    serving pod that follows deserializes instead of compiling —
    single-digit-second first audit. Prints one JSON summary line."""
    import json
    import os

    p = argparse.ArgumentParser(
        prog="gatekeeper-tpu warm-cache",
        description="pre-compile + serialize device programs for a "
                    "snapshotted workload (bake warm caches into "
                    "images/volumes)")
    p.add_argument("--state-dir", required=True,
                   help="state dir holding the snapshots to prepack "
                        "for; the AOT store is written to "
                        "<state-dir>/aot unless --aot-dir overrides")
    p.add_argument("--aot-dir", default="")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent XLA cache dir to populate "
                        "(GATEKEEPER_TPU_COMPILE_CACHE equivalent)")
    p.add_argument("--enabled", default="true",
                   help="false = exit 0 without prepacking (lets the "
                        "chart's prewarm initContainer stay templated "
                        "unconditionally and gate on the value)")
    p.add_argument("--log-level", default="INFO")
    args = p.parse_args(argv)
    glog.setup(args.log_level)
    if str(args.enabled).strip().lower() in ("false", "0", "no", "off"):
        print(json.dumps({"skipped": "prewarm disabled"}))
        return 0
    if args.compile_cache_dir:
        os.environ["GATEKEEPER_TPU_COMPILE_CACHE"] = args.compile_cache_dir
    from .statestore import StateStore, restore_section
    store = StateStore(args.state_dir)
    driver = TpuDriver(aot_dir=args.aot_dir or store.aot_dir())
    # this run IS the compile pass: no background warm, no host
    # fallback — trace/lower/compile inline and persist everything,
    # minting durable (serializable) executables even when the XLA
    # cache answers the compile
    driver.async_warm = False
    driver.aot.force_durable = True
    client = Backend(driver).new_client([K8sValidationTarget()])
    restored = {}
    if hasattr(driver, "vocab_restore"):
        restored["vocab"] = restore_section(store, "vocab",
                                            driver.vocab_restore)
    restored["library"] = restore_section(
        store, "library", lambda snap: client.restore_library(snap))
    objects = 0

    def apply_inventory(snap):
        nonlocal objects
        if hasattr(driver, "inventory_restore"):
            objects = driver.inventory_restore(snap.get("tree") or {})

    restored["inventory"] = restore_section(store, "inventory",
                                            apply_inventory, blob=True)
    violations = None
    audit_s = None
    if objects:
        t0 = time.monotonic()
        violations = len(client.audit().results())
        audit_s = round(time.monotonic() - t0, 2)
    else:
        log.warning("no inventory snapshot to sweep; only ingestion-"
                    "time programs were prepacked — run against a "
                    "state dir with snapshots for full coverage")
    summary = {
        "restored": restored, "objects": objects,
        "violations": violations, "audit_s": audit_s,
        "aot": driver.aot.stats_snapshot(),
        "programs_stored": driver.aot.programs_count(),
        "compile_cache_enabled": driver.compile_cache_enabled,
    }
    print(json.dumps(summary))
    return 0


def preview_main(argv=None) -> int:
    """`gatekeeper-tpu preview`: what-if a candidate policy.

    POSTs a constraint (plus, optionally, a not-yet-installed
    ConstraintTemplate) to a running instance's /v1/preview and prints
    the violation counts + capped samples as JSON — the full cached
    inventory is swept on-device without enforcing anything. Point it at
    the webhook port (TLS, self-signed accepted) or an audit pod's
    --preview-port plaintext listener."""
    import json
    import ssl
    import urllib.request

    p = argparse.ArgumentParser(
        prog="gatekeeper-tpu preview",
        description="evaluate a candidate ConstraintTemplate/Constraint "
                    "against a running instance's cached inventory "
                    "without enforcing it")
    p.add_argument("--url", default="https://localhost:8443",
                   help="base URL of a running gatekeeper-tpu (webhook "
                        "port — TLS, self-signed accepted — or "
                        "http://host:port for an audit pod's plaintext "
                        "--preview-port)")
    p.add_argument("--constraint", required=True,
                   help="constraint manifest (YAML or JSON file; '-' "
                        "for stdin)")
    p.add_argument("--template", default="",
                   help="candidate ConstraintTemplate manifest (YAML or "
                        "JSON); omit to preview against the kind's "
                        "already-ingested template")
    p.add_argument("--limit", type=int, default=20,
                   help="violation samples to return (cap 500)")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="request timeout (a cold preview may wait out "
                        "one XLA compile)")
    args = p.parse_args(argv)

    def load_manifest(path: str) -> dict:
        raw = sys.stdin.read() if path == "-" else open(path).read()
        try:
            import yaml
            doc = yaml.safe_load(raw)
        except ImportError:
            doc = json.loads(raw)
        if not isinstance(doc, dict):
            raise SystemExit(f"{path}: expected one manifest object")
        return doc

    payload = {"constraint": load_manifest(args.constraint),
               "limit": args.limit}
    if args.template:
        payload["template"] = load_manifest(args.template)
    req = urllib.request.Request(
        args.url.rstrip("/") + "/v1/preview",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    ctx = None
    if args.url.startswith("https"):
        # the webhook serves a self-signed rotating cert; the preview
        # payload carries no secrets, so unverified TLS is the useful
        # default for an operator poking from a laptop
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    try:
        with urllib.request.urlopen(req, timeout=args.timeout,
                                    context=ctx) as resp:
            body, status = resp.read(), resp.status
    except urllib.error.HTTPError as e:
        body, status = e.read(), e.code
    except OSError as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 2
    try:
        print(json.dumps(json.loads(body), indent=2))
    except ValueError:
        sys.stdout.write(body.decode("utf-8", "replace") + "\n")
    return 0 if status == 200 else 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv[:1] == ["warm-cache"]:
        return warm_cache_main(argv[1:])
    if argv[:1] == ["preview"]:
        return preview_main(argv[1:])
    if argv[:1] == ["scan"]:
        from .scan import scan_main

        return scan_main(argv[1:])
    args = build_parser().parse_args(argv)
    glog.setup(args.log_level)
    runtime = Runtime(args)
    stop = threading.Event()

    def handle_signal(*_):
        stop.set()

    signal.signal(signal.SIGTERM, handle_signal)
    signal.signal(signal.SIGINT, handle_signal)
    if hasattr(signal, "SIGHUP"):
        # operator escape hatch: force an immediate state snapshot
        # (e.g. right before a node drain) without restarting
        signal.signal(signal.SIGHUP,
                      lambda *_: runtime.snapshot_now())
    runtime.start()
    stop.wait()
    runtime.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
