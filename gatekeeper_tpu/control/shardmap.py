"""Consistent-hash shard map for the sharded audit inventory plane.

The audit inventory is partitioned by (GVK, namespace): every object of
one kind in one namespace (namespace "" for cluster-scoped objects)
lands on exactly one audit shard, which owns that slice end to end —
its watch deltas, encoded feature rows, delta cache and incremental
sweep state. The map must be

  * deterministic ACROSS PROCESSES: the leader routes inventory ops and
    every shard engine filters its own review set from the same key,
    so both sides must compute the same owner. Python's builtin
    ``hash()`` is salted per process and therefore banned here —
    positions come from blake2b over the canonical key string.
  * stable under resizing: growing 2 -> 4 shards must move ~1/2 of the
    keys (the consistent-hashing contract), not rehash the world. Each
    shard projects ``vnodes`` virtual points onto a 64-bit ring and a
    key belongs to the first point clockwise from its own position.

The leader owns ONE ShardMap instance per topology and bumps
``version`` on every (re)assignment so the rebalance metrics series
(`gatekeeper_tpu_audit_shard_map_version`,
`gatekeeper_tpu_audit_shard_rebalanced_total`) can tell a settled map
from one that is still churning.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional

GVK = tuple  # (group, version, kind) — control/kube.py convention


def _point(token: str) -> int:
    """64-bit ring position of a token, stable across processes."""
    return int.from_bytes(
        hashlib.blake2b(token.encode("utf-8"), digest_size=8).digest(),
        "big")


def partition_key(gvk: GVK, namespace: str = "") -> str:
    """Canonical partition-key string for (GVK, namespace). Cluster-
    scoped objects use namespace "" — one owner per cluster-scoped
    kind, by design (the ISSUE's partition unit is (GVK, namespace))."""
    group, version, kind = gvk
    return f"{group or ''}|{version or ''}|{kind or ''}|{namespace or ''}"


class ShardMap:
    """The ring: `shards` shards x `vnodes` virtual points each."""

    def __init__(self, shards: int, vnodes: int = 64):
        if shards < 1:
            raise ValueError("shard count must be >= 1")
        self.shards = int(shards)
        self.vnodes = int(vnodes)
        self.version = 1
        self._points: list[int] = []
        self._owners: list[int] = []
        for k in range(self.shards):
            for v in range(self.vnodes):
                self._points.append(_point(f"audit-shard:{k}:{v}"))
                self._owners.append(k)
        order = sorted(range(len(self._points)),
                       key=lambda i: self._points[i])
        self._points = [self._points[i] for i in order]
        self._owners = [self._owners[i] for i in order]

    def owner(self, gvk: GVK, namespace: str = "") -> int:
        """Shard index owning (GVK, namespace)."""
        if self.shards == 1:
            return 0
        h = _point(partition_key(gvk, namespace))
        i = bisect.bisect_right(self._points, h)
        if i == len(self._points):
            i = 0  # wrap: the ring is circular
        return self._owners[i]

    def owner_of_obj(self, gvk: GVK, obj: dict) -> int:
        ns = ((obj or {}).get("metadata") or {}).get("namespace") or ""
        return self.owner(gvk, ns)

    def owns(self, shard: int, gvk: GVK, namespace: str = "") -> bool:
        return self.owner(gvk, namespace) == int(shard)

    # ------------------------------------------------------- rebalancing

    def rebalance(self, shards: int,
                  keys: Optional[Iterable[tuple]] = None) -> dict:
        """Re-assign the ring for a new shard count. Returns
        {"moved": n, "total": n, "fraction": f} over `keys` (an
        iterable of (gvk, namespace) partition keys; empty -> zeros) so
        the caller can export how much of the inventory the resize
        displaced — ~|new-old|/max(new,old) for a healthy ring, ~1.0
        for a broken (mod-N style) one. Bumps `version` even when no
        key moved: the assignment epoch changed either way."""
        old = ShardMap(self.shards, self.vnodes)
        version = self.version
        self.__init__(shards, self.vnodes)  # rebuild the ring in place
        self.version = version + 1
        moved = total = 0
        for gvk, ns in keys or ():
            total += 1
            if old.owner(gvk, ns) != self.owner(gvk, ns):
                moved += 1
        return {"moved": moved, "total": total,
                "fraction": (moved / total) if total else 0.0}

    def assignment_counts(self, keys: Iterable[tuple]) -> list[int]:
        """Objects-per-shard histogram over (gvk, namespace) keys — the
        ownership gauge's source (skew is the thing to watch: one hot
        namespace pins its whole slice to one shard)."""
        counts = [0] * self.shards
        for gvk, ns in keys:
            counts[self.owner(gvk, ns)] += 1
        return counts
