"""Admission serving plane: pre-fork HTTP frontends over a shared
batching backplane.

The single-process webhook frontend is GIL-bound: BENCH_r05 config 5
showed the engine sustaining ~6,000 batched reviews/s while one Python
HTTP frontend delivered ~500 req/s. The reference line scales its Go
webhook by replicating pods behind a Service; the TPU-native analogue
must keep ONE device-owning engine so micro-batches stay full. So the
plane splits:

    API server ──TLS──► frontend 0 ─┐
    API server ──TLS──► frontend 1 ─┼─UDS─► engine (JAX + Client +
    API server ──TLS──► frontend N ─┘        MicroBatcher + handlers)

N pre-forked frontend processes (one GIL each) bind the webhook port
with SO_REUSEPORT and do ONLY accept / TLS / header parse; the request
body rides the backplane as opaque bytes — frontends never JSON-decode
a review. The engine decodes once, submits into the SHARED MicroBatcher
(requests from all workers coalesce into the same device micro-batch:
cross-worker batching is the point — N trickles become one full batch),
and answers with preserialized envelope bytes the frontend writes
straight to its HTTP socket.

Wire protocol, length-prefixed frames over a Unix domain socket
(multiplexed: many in-flight requests per frontend connection):

    frame    := u32be payload_len, payload
    payload  := type(1 byte) + body
    'Q'      := id u32be, timeout_s f64be (0 = absent), tflags u8,
                [tflags&1: trace_id 16 bytes, t_recv f64be,
                t_fwd f64be], path_len u16be, path bytes,
                [tflags&2: ring_off u32be, ring_len u32be — the review
                lives in the frontend's request RING; else:] review
                bytes                               (frontend -> engine)
    'R'      := id u32be, http_status u16be, body   (engine -> frontend)
    'r'      := id u32be, http_status u16be, ring_off u32be,
                ring_len u32be (response bytes live in the frontend's
                reply RING)                         (engine -> frontend)
    'H'      := hello JSON {"worker": id, "rings": {"q":..., "r":...}}
                                                    (frontend -> engine)
    'A'      := ack JSON {"rings": bool} — whether the engine attached
                the hello's rings; descriptors flow only after a true
                ack                                 (engine -> frontend)
    'S'      := stats JSON (aggregated forward-latency histogram delta
                + failure-stance answer count + per-stage span-duration
                histogram deltas for sampled requests) (frontend -> engine)
    'L'      := id u32be, library-op JSON           (primary -> engine)
    'M'      := id u32be (stats poll; engine answers R with its
                relayed-metrics snapshot JSON)      (primary -> engine)
    'B'      := id u32be, timeout_s f64be, count u32be, count x
                (u32be len, review bytes) — BULK binary ingest: the
                whole batch feeds the MicroBatcher pre-parsed and the
                answer is an R frame of count x (u32be len, envelope
                bytes). The streaming path for CI scanners / service-
                mesh authorizers that skip HTTP framing entirely
                                                    (caller -> engine)

Shared-memory rings (tflags&2 / 'r' frames, control/shm.py): each
frontend owns a request ring + a reply ring; review bytes are written
ring-side at accept time and the frames carry (offset, length)
descriptors, so the socket — which remains the ordering and wakeup
channel — moves ~40 bytes per review instead of the payload. The
engine parses reviews out of the mapped ring (zero payload copies
across the backplane) and writes response envelopes into the reply
ring the same way. A burst that outruns the reader falls back to
inline-payload frames per request (alloc returns None past the
watermark); the accept loop never blocks on ring space.

N-engine plane (--admission-engines > 1): one engine PROCESS per chip,
each with its own Client/MicroBatcher/device and its own socket
(`<base>.<k>`); frontends hold one multiplexed connection per engine
and route each review to the least-loaded engine (fallback:
request-hash), failing over to the next engine when one dies
mid-burst. The PRIMARY process (engine 0, in-process) replicates every
library mutation to every engine child over L frames — each child's
Client bumps its own generation when the op lands, so decision-cache
keys stay coherent per engine — and polls per-engine metric totals
over M frames, merging deltas into its registry so shed accounting and
decision counts stay global.

Span context over the split: the FRONTEND makes the sampling decision
at the HTTP edge (it parses `traceparent`, answers `X-Trace-Id`); a
sampled request's Q frame carries the trace id plus the frontend's
receive/forward monotonic instants (CLOCK_MONOTONIC is system-wide, so
the engine compares them directly), and the engine reconstructs the
frontend_parse and backplane_forward spans, then times its own stages.
An UNSAMPLED request pays one zero byte on the wire and no span
allocations anywhere.

Resilience contract across the split:
  * deadlines propagate — the frame carries the request's timeout and
    the engine pins the absolute deadline AT FRAME RECEIPT, so executor
    queueing spends the request's budget, not a fresh one;
  * frontends answer per the fail-open/closed stance when the engine is
    unreachable or a verdict never lands (fault point
    `backplane.engine` arms that path for chaos runs);
  * shed accounting stays ENGINE-side (`--admission-max-queue` bounds
    the one shared batcher), so the bound is global, not per-worker;
  * SIGTERM drains frontends BEFORE the engine: the supervisor TERMs
    its children (each stops accepting, finishes in-flight HTTP
    requests), then the engine drains the shared batcher.
"""

from __future__ import annotations

import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from ..utils import faults
from . import jsonio
from . import liveness
from . import shm
from . import trace as gtrace
from .logging import logger
from .webhook import (
    DEFAULT_WEBHOOK_TIMEOUT_S,
    MAX_WEBHOOK_TIMEOUT_S,
    encode_envelope,
    parse_timeout_query,
    request_deadline,
    route_path,
)

log = logger("backplane")

_Q_HEADER = struct.Struct("!Id")   # request id, timeout seconds
_Q_TRACE = struct.Struct("!16sdd")  # trace id, t_recv, t_fwd (monotonic)
_Q_PATHLEN = struct.Struct("!H")
_Q_RING = struct.Struct("!II")     # request-ring offset, payload length
_R_HEADER = struct.Struct("!IH")   # request id, http status
_R_RING = struct.Struct("!IHII")   # id, status, reply-ring offset, length
_B_HEADER = struct.Struct("!IdI")  # request id, timeout seconds, count
_B_LEN = struct.Struct("!I")
# tflags bits on Q frames
TF_TRACE = 0x1   # span context follows
TF_RING = 0x2    # body is a request-ring descriptor, not inline bytes

# frontends bucket forward latencies with the same bounds the engine
# registry renders — one constant, no drift into mislabeled buckets
from .metrics import FORWARD_BUCKETS as STATS_BUCKETS  # noqa: E402
from .metrics import STAGE_BUCKETS  # noqa: E402


def _bucket_observe(counts: list, bounds: tuple, seconds: float) -> None:
    """Accumulate one observation into a local histogram delta
    (counts carries len(bounds)+1 slots; the last is +Inf)."""
    for i, b in enumerate(bounds):
        if seconds <= b:
            counts[i] += 1
            return
    counts[-1] += 1

STATS_INTERVAL_S = 2.0
# R-frame status an engine answers while it is NOT READY to serve (a
# respawned engine child before its library sync): never surfaces as an
# HTTP verdict — the router fails the request over to a synced engine
STATUS_NOT_READY = 599
# per-operation socket timeout on backplane I/O: a WEDGED (not dead)
# peer must unblock senders so frontends can answer per the failure
# stance instead of hanging HTTP threads past their deadlines
IO_TIMEOUT_S = 2.0
# frame hygiene: upper bound on any length prefix accepted at parse
# time. A desynced/corrupted u32 (mid-stream reset, flipped bit) would
# otherwise commit the reader to recv'ing gigabytes of garbage and then
# smear every subsequent parse; an oversized header is treated as a
# torn stream — clean connection close, the client re-handshakes. Must
# comfortably exceed the largest legal frame (bulk B frames carry whole
# inventory slices; admission bodies are ~MBs at worst).
MAX_FRAME_LEN = 256 * 1024 * 1024


class FrameDesyncError(ConnectionError):
    """A length prefix failed the hygiene bound — the stream is torn
    (desynced or corrupted) and the only safe recovery is to drop the
    connection and re-handshake."""


def _check_frame_len(length: int) -> int:
    if length > MAX_FRAME_LEN:
        raise FrameDesyncError(
            f"backplane frame length {length} exceeds bound "
            f"{MAX_FRAME_LEN}; closing desynced connection")
    return length


class BackplaneError(Exception):
    """The engine could not be reached / the verdict never arrived —
    the frontend answers per the failure stance."""


# Q-frame body sentinels on the engine side: the review either arrived
# pre-parsed off the request ring, is still raw bytes, or was a ring
# payload that failed to parse (a torn slot after a cancel — answer 400)
_UNPARSED = object()
_BAD = object()


def default_socket_path() -> str:
    import tempfile

    return os.path.join(tempfile.gettempdir(),
                        f"gatekeeper-tpu-backplane-{os.getpid()}.sock")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes. A socket TIMEOUT retries without losing
    the partial buffer (sockets carry a per-operation timeout so a
    wedged peer unblocks SENDERS; an idle reader just waits on)."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except TimeoutError:
            continue
        if not chunk:
            raise ConnectionError("backplane peer closed")
        buf += chunk
    return bytes(buf)


def _send_frame(sock: socket.socket, lock: threading.Lock,
                *parts) -> None:
    """Send one length-prefixed frame as a vectored write.

    The previous implementation concatenated `struct.pack("!I", n) +
    b"".join(parts)` — a full extra copy of every payload per frame, on
    top of the kernel's own. `sendmsg` hands the header and payload
    buffers to the kernel as an iovec instead; the rare partial send
    (payload larger than the socket buffer under backpressure) falls
    back to flattening just the unsent remainder."""
    plen = sum(len(p) for p in parts)
    header = struct.pack("!I", plen)
    wire_fault = faults.consume("backplane.wire")
    if wire_fault is not None:
        _fault_frame(sock, lock, header, parts, wire_fault)
        return
    bufs = (header, *parts)
    if len(bufs) > 1000:
        # sendmsg is capped at IOV_MAX (1024) iovecs — a bulk B frame
        # of >=500 reviews would hit EMSGSIZE and be misread as
        # connection loss; flatten once instead
        bufs = (header, b"".join(parts))
    with lock:
        try:
            sent = sock.sendmsg(bufs)
        except (AttributeError, NotImplementedError):
            # pragma: no cover - TLS/odd sockets (ssl raises
            # NotImplementedError, not AttributeError)
            sock.sendall(header + b"".join(parts))
            return
        if sent < 4 + plen:
            rest = b"".join(bufs)
            sock.sendall(memoryview(rest)[sent:])


def _fault_frame(sock: socket.socket, lock: threading.Lock,
                 header: bytes, parts: tuple, fault: tuple) -> None:
    """Act out an armed backplane.wire fault on this frame.

    reset    -> close the socket without sending a byte and raise as if
                the kernel reset the connection mid-frame
    truncate -> write the header + a partial payload, then close: the
                peer's length-prefixed read blocks on bytes that never
                come until its ConnectionError on the close
    slow     -> drip the frame out in small chunks with delays (frame
                eventually completes; exercises IO_TIMEOUT_S retries)
    """
    mode, param = fault
    frame = header + b"".join(parts)
    with lock:
        if mode == "reset":
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
            raise ConnectionResetError(
                "injected backplane.wire reset mid-frame")
        if mode == "truncate":
            cut = max(4, len(frame) // 2)
            try:
                sock.sendall(frame[:cut])
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
            raise ConnectionResetError(
                "injected backplane.wire truncated frame")
        # slow drip: param carries the per-chunk delay in seconds
        try:
            delay = float(param) if param else 0.05
        except ValueError:
            delay = 0.05
        chunk = 64
        for off in range(0, len(frame), chunk):
            sock.sendall(frame[off:off + chunk])
            # gklint: allow(block-zone) reason=the slow-drip fault EXISTS to stall this send; only reachable with backplane.wire armed by a chaos run
            time.sleep(delay)


# ----------------------------------------------------------------- engine


class BackplaneEngine:
    """The engine-side listener: owns the handlers (and through them the
    one shared MicroBatcher), decodes each forwarded review once, and
    answers with preserialized envelope bytes."""

    def __init__(self, socket_path: str, validation=None, ns_label=None,
                 mutation=None, max_workers: int = 128,
                 default_timeout: float = DEFAULT_WEBHOOK_TIMEOUT_S,
                 engine_id: str = "0", library_sink=None,
                 stats_source=None, preview=None, auditor=None):
        self.socket_path = socket_path
        self.validation = validation
        self.ns_label = ns_label
        self.mutation = mutation
        # what-if preview (control.preview.PreviewEngine): served on its
        # OWN single-thread executor, never the shared admission pool —
        # a multi-second inventory sweep must not occupy a thread an
        # admission verdict is waiting for
        self.preview = preview
        self._preview_pool = None
        # audit shard server (control.audit.AuditSliceServer): same
        # isolation contract as preview — a slice sweep is a multi-
        # second evaluation and rides its own single-thread executor
        self.auditor = auditor
        self._audit_pool = None
        self.default_timeout = default_timeout
        self.engine_id = str(engine_id)
        # L-frame handler (engine children): applies one replicated
        # library op to this engine's Client/MutationSystem
        self.library_sink = library_sink
        # M-frame handler: answers the primary's stats poll (defaults
        # to the registry's relay snapshot in engine children)
        self.stats_source = stats_source
        # when set, Q frames answer STATUS_NOT_READY until it returns
        # True: a RESPAWNED engine child must not serve admission
        # verdicts from its empty pre-sync library — the router fails
        # those requests over to a synced engine
        self.ready_check: Optional[Callable[[], bool]] = None
        self._max_workers = max_workers
        self._listener: Optional[socket.socket] = None
        self._pool = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._conns: dict[int, tuple] = {}  # fd -> (sock, wlock, worker)
        self._conns_lock = threading.Lock()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.configured_workers = 0  # set by the Runtime for the gauge

    # lifecycle ------------------------------------------------------

    def start(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self._pool = ThreadPoolExecutor(
            max_workers=self._max_workers,
            thread_name_prefix="backplane-serve")
        if self.preview is not None:
            self._preview_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="preview-serve")
        if self.auditor is not None:
            self._audit_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="auditslice-serve")
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="backplane-accept", daemon=True)
        self._accept_thread.start()

        def _probe():
            from . import metrics
            with self._inflight_lock:
                n = self._inflight
            metrics.report_queue_depth("backplane_engine", n,
                                       engine=self.engine_id)

        from . import metrics as _metrics
        _metrics.register_saturation_probe(
            f"backplane-engine-{self.engine_id}", _probe)
        log.info("backplane engine listening",
                 details={"socket": self.socket_path})

    def alive(self) -> bool:
        t = self._accept_thread
        return bool(t and t.is_alive()) and not self._stop.is_set()

    @property
    def connected(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    def abort(self) -> None:
        """Drop dead NOW — no drain, no batcher teardown. The chaos
        suite uses this to emulate an engine crash (kill -9) under a
        live burst: every frontend's in-flight forward fails over to
        the failure-stance answer."""
        from . import metrics
        metrics.unregister_saturation_probe(
            f"backplane-engine-{self.engine_id}")
        metrics.report_queue_depth("backplane_engine", 0,
                                   engine=self.engine_id)
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock, _wlock, _worker in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def stop(self, drain_timeout: float = 10.0) -> None:
        """Called AFTER the frontends drained: no new frames arrive, so
        finish the in-flight verdicts, drain the shared batcher, and
        tear the listener down."""
        from . import metrics
        metrics.unregister_saturation_probe(
            f"backplane-engine-{self.engine_id}")
        metrics.report_queue_depth("backplane_engine", 0,
                                   engine=self.engine_id)
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        end = time.monotonic() + drain_timeout
        while time.monotonic() < end:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        for handler in (self.validation, self.mutation):
            if handler is not None:
                handler.batcher.drain(max(0.5, end - time.monotonic()))
                handler.batcher.stop()
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
        if self._preview_pool is not None:
            self._preview_pool.shutdown(wait=False, cancel_futures=True)
        if self._audit_pool is not None:
            self._audit_pool.shutdown(wait=False, cancel_futures=True)
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock, _wlock, _worker in conns:
            try:
                sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    # accept / read --------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            # generous per-op timeout: a stuck FRONTEND must not pin an
            # engine worker thread in sendall forever (the supervisor
            # respawns it and the dead conn errors out)
            conn.settimeout(30.0)
            wlock = threading.Lock()
            with self._conns_lock:
                self._conns[conn.fileno()] = (conn, wlock, None)
            threading.Thread(target=self._read_loop, args=(conn, wlock),
                             name="backplane-read", daemon=True).start()

    def _read_loop(self, conn: socket.socket, wlock: threading.Lock) -> None:
        fd = conn.fileno()
        rings = None  # this frontend's shm ring pair, attached on hello
        try:
            while not self._stop.is_set():
                (length,) = struct.unpack("!I", _recv_exact(conn, 4))
                payload = _recv_exact(conn, _check_frame_len(length))
                kind = payload[:1]
                if kind == b"Q":
                    rid, timeout_s = _Q_HEADER.unpack_from(payload, 1)
                    if self.ready_check is not None \
                            and not self.ready_check():
                        _send_frame(conn, wlock, b"R",
                                    _R_HEADER.pack(rid,
                                                   STATUS_NOT_READY),
                                    b"engine awaiting library sync")
                        continue
                    off = 1 + _Q_HEADER.size
                    tflags = payload[off]
                    off += 1
                    tr = gtrace.NOOP
                    if tflags & TF_TRACE:
                        # sampled: reconstruct the frontend-side spans
                        # from the carried span context (same-host
                        # CLOCK_MONOTONIC). frontend_parse is remote —
                        # the frontend ships its histogram delta over S
                        # frames, so the engine's metrics sink must not
                        # double it. backplane_forward (t_fwd -> frame
                        # receipt) is timed HERE and histogrammed here:
                        # it is the true one-way hop — the frontend
                        # only knows its full call round trip, which
                        # would re-count every engine stage
                        tid, t_recv, t_fwd = _Q_TRACE.unpack_from(
                            payload, off)
                        off += _Q_TRACE.size
                        tr = gtrace.TRACER.resume(gtrace.ADMISSION,
                                                  tid.hex())
                        tr.t0 = t_recv  # the trace starts at the edge
                        tr.add_span("frontend_parse", t_recv, t_fwd,
                                    remote=True)
                        tr.add_span("backplane_forward", t_fwd,
                                    time.monotonic())
                    (plen,) = _Q_PATHLEN.unpack_from(payload, off)
                    off += _Q_PATHLEN.size
                    path = payload[off:off + plen].decode("ascii", "replace")
                    off += plen
                    review = _UNPARSED
                    body = b""
                    if tflags & TF_RING and rings is not None:
                        # descriptor frame: the review lives in this
                        # frontend's request ring. Parse it HERE, zero-
                        # copy off the mapped segment, so the slot
                        # releases in FIFO order with the descriptors —
                        # the engine's only per-review byte work is the
                        # JSON decode it had to do anyway.
                        roff, rlen = _Q_RING.unpack_from(payload, off)
                        t_ring0 = time.monotonic() if tr.sampled else 0.0
                        try:
                            review = jsonio.loads(
                                rings.req.view(roff, rlen))
                        except ValueError:
                            review = _BAD
                        finally:
                            rings.req.release(roff)
                        if tr.sampled:
                            tr.add_span("ring_read", t_ring0,
                                        time.monotonic())
                        if review is not _BAD \
                                and route_path(path) in ("preview",
                                                         "auditslice"):
                            # previews/audit sweeps consume raw body
                            # bytes (the client avoids the ring for
                            # them; this is the defensive path)
                            body = jsonio.dumps_bytes(review)
                            review = _UNPARSED
                    else:
                        body = payload[off:]
                    # deadline pinned HERE: queueing ahead of the serve
                    # call spends the request's own budget
                    deadline = request_deadline(
                        {"timeoutSeconds": timeout_s} if timeout_s > 0
                        else {}, self.default_timeout)
                    # fast path: decision-cache hits, short-circuits,
                    # and the namespace-label check are answered INLINE
                    # — no thread handoff on the hot path. Only
                    # requests that must evaluate take the pool (which
                    # reuses the already-parsed review).
                    try:
                        inline = self._try_inline(timeout_s, deadline,
                                                  path, body, tr,
                                                  review=review)
                    except Exception as e:
                        log.error("backplane inline serve error",
                                  details=str(e))
                        inline = (500, b"")
                    if inline[0] not in ("eval", "eval-preview",
                                         "eval-audit"):
                        # a failed/partial send desyncs the stream:
                        # close and let the frontend reconnect
                        t_send = time.monotonic()
                        self._respond_frame(conn, wlock, rings, rid,
                                            inline[0], inline[1])
                        if tr.sampled:
                            tr.add_span("respond", t_send,
                                        time.monotonic())
                            tr.finish()
                        continue
                    with self._inflight_lock:
                        self._inflight += 1
                    # preview/audit sweeps ride their own single-thread
                    # executors: admission verdicts never queue behind
                    # a multi-second inventory evaluation
                    pool = (self._preview_pool
                            if inline[0] == "eval-preview"
                            else self._audit_pool
                            if inline[0] == "eval-audit"
                            else self._pool)
                    pool.submit(self._serve, conn, wlock, rid,
                                timeout_s, deadline, path, body,
                                inline[1], tr, time.monotonic(), rings)
                elif kind == b"B":
                    # BULK binary ingest: one frame, many pre-framed
                    # reviews, fed to the MicroBatcher as one submit —
                    # the streaming path for callers that skip HTTP
                    rid, timeout_b, count = _B_HEADER.unpack_from(
                        payload, 1)
                    if self.ready_check is not None \
                            and not self.ready_check():
                        _send_frame(conn, wlock, b"R",
                                    _R_HEADER.pack(rid,
                                                   STATUS_NOT_READY),
                                    b"engine awaiting library sync")
                        continue
                    deadline = request_deadline(
                        {"timeoutSeconds": timeout_b} if timeout_b > 0
                        else {}, self.default_timeout)
                    with self._inflight_lock:
                        self._inflight += 1
                    self._pool.submit(self._serve_bulk, conn, wlock,
                                      rid, deadline, payload, count)
                elif kind == b"H":
                    info = jsonio.loads(payload[1:]) or {}
                    worker = str(info.get("worker", "?"))
                    with self._conns_lock:
                        if fd in self._conns:
                            self._conns[fd] = (conn, wlock, worker)
                    self._report_workers()
                    ring_names = info.get("rings")
                    if ring_names:
                        ack = False
                        try:
                            rings = shm.EngineRings(ring_names)
                            ack = True
                        except Exception as e:
                            rings = None
                            log.warning(
                                "ring attach failed; inline payloads",
                                details=str(e))
                        _send_frame(conn, wlock, b"A",
                                    jsonio.dumps_bytes({"rings": ack}))
                    log.info("frontend connected",
                             details={"worker": worker,
                                      "rings": rings is not None})
                elif kind == b"S":
                    self._merge_stats(jsonio.loads(payload[1:]) or {})
                elif kind == b"L":
                    # replicated library op from the primary: applied
                    # INLINE on this read loop, so ops from the one
                    # control connection apply in send order (the
                    # engine's own Client bumps its generation under
                    # the op — decision-cache coherence needs no extra
                    # fence). Admission traffic rides the frontends'
                    # separate connections, unaffected.
                    (rid,) = struct.unpack("!I", payload[1:5])
                    status, out = 200, b""
                    try:
                        if self.library_sink is None:
                            status = 404
                        else:
                            self.library_sink(jsonio.loads(payload[5:])
                                              or {})
                    except Exception as e:
                        log.error("library replication op failed",
                                  details=str(e))
                        status = 500
                        out = str(e).encode("utf-8", "replace")[:512]
                    _send_frame(conn, wlock, b"R",
                                _R_HEADER.pack(rid, status), out)
                elif kind == b"M":
                    (rid,) = struct.unpack("!I", payload[1:5])
                    try:
                        src = self.stats_source
                        stats = src() if src is not None else {}
                        _send_frame(conn, wlock, b"R",
                                    _R_HEADER.pack(rid, 200),
                                    jsonio.dumps_bytes(stats))
                    except Exception as e:
                        log.error("stats poll failed", details=str(e))
                        _send_frame(conn, wlock, b"R",
                                    _R_HEADER.pack(rid, 500), b"")
        except FrameDesyncError as e:
            log.error("backplane frame desync; dropping connection",
                      details=str(e))
        except (ConnectionError, OSError):
            pass
        finally:
            with self._conns_lock:
                ent = self._conns.pop(fd, None)
            worker = ent[2] if ent else None
            if worker is not None:
                # gauges only ever SET: a frontend that died mid-burst
                # would otherwise export its last (high) in-flight
                # forever — zero it, since a dead frontend truthfully
                # has nothing in flight
                try:
                    from . import metrics
                    metrics.report_backplane_inflight(worker, 0)
                    if rings is not None:
                        metrics.report_ring_fill(worker, 0.0)
                except Exception:
                    pass
            if rings is not None:
                # engine-side DETACH on connection loss: the frontend
                # (or its supervisor) owns unlinking; any in-flight
                # descriptors already failed with their waiters
                rings.close()
            try:
                conn.close()
            except OSError:
                pass
            if not self._stop.is_set():
                self._report_workers()

    def _report_workers(self) -> None:
        from . import metrics

        metrics.report_admission_workers(self.configured_workers,
                                         self.connected)

    def _merge_stats(self, stats: dict) -> None:
        from . import metrics

        worker = str(stats.get("worker", "?"))
        counts = stats.get("buckets") or []
        n = int(stats.get("count") or 0)
        if n:
            metrics.report_backplane_forward(
                worker, counts, float(stats.get("sum") or 0.0), n)
        errs = int(stats.get("errors") or 0)
        if errs:
            metrics.report_backplane_error(worker, errs)
        if "inflight" in stats:
            # sampled per stats interval: how many forwarded reviews
            # this frontend is still waiting on — the saturation read
            # that separates "frontends backed up" from "engine idle"
            metrics.report_backplane_inflight(
                worker, int(stats.get("inflight") or 0))
        # ring-path accounting: how many of this frontend's forwards
        # rode the shm ring vs fell back to inline payloads (burst
        # outran the reader / oversized review), plus the request
        # ring's sampled fill fraction — the "is the ring sized right"
        # read off one scrape
        for pth, n in (stats.get("ring_paths") or {}).items():
            if n:
                metrics.report_backplane_ring(worker, str(pth), int(n))
        if "ring_fill" in stats:
            metrics.report_ring_fill(
                worker, float(stats.get("ring_fill") or 0.0))
        # frontend-side span deltas (sampled requests only): each
        # frontend ships aggregated histograms for the stages it owns
        # (frontend_parse) — the engine's trace sink skips those
        # remote spans so they are counted exactly once
        from .stages import STAGE_NAMES
        for stage, d in (stats.get("stages") or {}).items():
            n = int(d.get("count") or 0)
            if n and str(stage) in STAGE_NAMES:
                # wire-supplied names bounded against the central
                # stage registry: a version-skewed frontend cannot
                # mint label series the dashboards don't know
                # gklint: allow(stage) reason=runtime-folded against control/stages.py STAGE_NAMES on the line above
                metrics.report_stage_bucketed(
                    "admission", str(stage), d.get("buckets") or [],
                    float(d.get("sum") or 0.0), n)

    # serve ----------------------------------------------------------

    @staticmethod
    def _fold_timeout(review, timeout_s: float, deadline: float):
        """Merge the frame's ?timeout= budget into the request and pick
        the effective deadline: a body-carried timeoutSeconds (tests /
        direct callers) WINS over the frame's query budget — matching
        the single-process server — in which case the handler derives
        the deadline from the body (deadline=None)."""
        request = (review or {}).get("request") \
            if isinstance(review, dict) else None
        if not isinstance(request, dict):
            return deadline
        if "timeoutSeconds" in request:
            return None
        if timeout_s > 0:
            request["timeoutSeconds"] = timeout_s
        return deadline

    def _try_inline(self, timeout_s: float, deadline: float, path: str,
                    body: bytes, tr=gtrace.NOOP, review=_UNPARSED) -> tuple:
        """(status, payload) when the verdict needs no blocking work
        (cache hit / short-circuit / namespace-label check / 404);
        ("eval", parsed_review_or_None) hands it to the worker pool.
        `review` carries the pre-parsed review when the body arrived as
        a ring descriptor (the read loop decodes it zero-copy)."""
        route = route_path(path)
        if review is _BAD:
            # a ring slot that failed to parse: torn by a cancel (the
            # waiter is already gone) or a corrupt writer — 400, never
            # a handler call on garbage
            return (400, b"")
        if route == "admitlabel":
            if self.ns_label is None:
                return (404, b"")
            if review is _UNPARSED:
                try:
                    review = jsonio.loads(body)
                except ValueError:
                    return (400, b"")
            return (200, encode_envelope(self.ns_label.handle(review)))
        if route == "admit":
            if self.validation is None:
                return (404, b"")
            if review is _UNPARSED:
                try:
                    review = jsonio.loads(body)
                except ValueError:
                    return (400, b"")
            eff_deadline = self._fold_timeout(review, timeout_s, deadline)
            out = self.validation.handle(review, deadline=eff_deadline,
                                         fast=True, trace=tr)
            if out is None:
                # cache miss: evaluation needs a thread; hand over the
                # parsed review AND the folded deadline
                return ("eval", (review, eff_deadline))
            if not tr.sampled:
                return (200, encode_envelope(out))
            with tr.span("serialize"):
                payload = encode_envelope(out)
            return (200, payload)
        if route == "mutate":
            if self.mutation is None:
                return (404, b"")
            if review is _UNPARSED:
                # inline payload: parse on the pool thread, off the
                # read loop (mutation payloads can be large)
                return ("eval", None)
            return ("eval",
                    (review, self._fold_timeout(review, timeout_s,
                                                deadline)))
        if route == "preview":
            return ("eval-preview", None) if self.preview is not None \
                else (404, b"")
        if route == "auditslice":
            return ("eval-audit", None) if self.auditor is not None \
                else (404, b"")
        return (404, b"")

    def _respond_frame(self, conn, wlock, rings, rid: int, status: int,
                       out: bytes) -> None:
        """Answer one Q frame: descriptor over the reply ring when the
        frontend has one and the payload fits (zero payload copies on
        the socket), else the inline R frame. Raises OSError upward —
        the caller owns desync handling."""
        if rings is not None and out:
            try:
                roff = rings.reply.append(out)
            except (TypeError, ValueError):  # ring torn down mid-serve
                roff = None
            if roff is not None:
                try:
                    _send_frame(conn, wlock, b"r",
                                _R_RING.pack(rid, status, roff,
                                             len(out)))
                except OSError:
                    try:
                        rings.reply.cancel(roff)
                    except (TypeError, ValueError):
                        pass
                    raise
                return
        _send_frame(conn, wlock, b"R", _R_HEADER.pack(rid, status), out)

    def _serve(self, conn: socket.socket, wlock: threading.Lock,
               rid: int, timeout_s: float, deadline: float, path: str,
               body: bytes, handoff=None, tr=gtrace.NOOP,
               t_queued: float = 0.0, rings=None) -> None:
        review = None
        if handoff is not None:
            review, deadline = handoff
        if tr.sampled:
            # executor queue wait: frame receipt -> a pool thread
            # actually picked the request up
            tr.add_span("engine_queue", t_queued, time.monotonic())
        try:
            status, out = self._decide(timeout_s, deadline, path, body,
                                       review=review, tr=tr)
            t_send = time.monotonic()
            try:
                self._respond_frame(conn, wlock, rings, rid, status,
                                    out)
            except OSError:
                # frontend died or the send timed out mid-frame — the
                # stream may be desynced, so close it (the supervisor
                # respawns the worker, which reconnects clean)
                try:
                    conn.close()
                except OSError:
                    pass
            if tr.sampled:
                tr.add_span("respond", t_send, time.monotonic())
                tr.finish()
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _serve_bulk(self, conn: socket.socket, wlock: threading.Lock,
                    rid: int, deadline: float, payload: bytes,
                    count: int) -> None:
        """One B frame: parse every length-prefixed review, feed the
        whole batch to the MicroBatcher via handle_bulk (one enqueue
        pass, shared seals), answer count x (len, envelope) in one R
        frame."""

        def send(*parts):
            # any partial/failed send desyncs the multiplexed stream:
            # close so the caller reconnects clean (same contract as
            # _serve)
            try:
                _send_frame(conn, wlock, *parts)
            except OSError:
                try:
                    conn.close()
                except OSError:
                    pass

        try:
            reviews = []
            off = 1 + _B_HEADER.size
            try:
                for _ in range(count):
                    (n,) = _B_LEN.unpack_from(payload, off)
                    off += _B_LEN.size
                    reviews.append(
                        jsonio.loads(memoryview(payload)[off:off + n]))
                    off += n
            except (ValueError, struct.error):
                send(b"R", _R_HEADER.pack(rid, 400), b"")
                return
            if self.validation is None:
                send(b"R", _R_HEADER.pack(rid, 404), b"")
                return
            try:
                outs = self.validation.handle_bulk(reviews, deadline)
            except Exception as e:
                log.error("bulk ingest failed", details=str(e))
                send(b"R", _R_HEADER.pack(rid, 500),
                     str(e).encode("utf-8", "replace")[:512])
                return
            parts = [_R_HEADER.pack(rid, 200), _B_LEN.pack(len(outs))]
            for env in outs:
                item = encode_envelope(env)
                parts.append(_B_LEN.pack(len(item)))
                parts.append(item)
            send(b"R", *parts)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _decide(self, timeout_s: float, deadline: float, path: str,
                body: bytes, review=None,
                tr=gtrace.NOOP) -> tuple[int, bytes]:
        if review is None:
            try:
                review = jsonio.loads(body)
            except ValueError:
                return 400, b""
            deadline = self._fold_timeout(review, timeout_s, deadline)
        # (a review handed over by _try_inline already has the timeout
        # folded and the deadline pinned at frame receipt)
        route = route_path(path)
        try:
            if route == "preview" and self.preview is not None:
                return self.preview.handle_http(body)
            if route == "auditslice" and self.auditor is not None:
                return self.auditor.handle_http(body)
            if route == "admitlabel" and self.ns_label is not None:
                out = self.ns_label.handle(review)
            elif route == "admit" and self.validation is not None:
                out = self.validation.handle(review, deadline=deadline,
                                             trace=tr)
            elif route == "mutate" and self.mutation is not None:
                out = self.mutation.handle(review, deadline=deadline,
                                           trace=tr)
            else:
                return 404, b""
            if not tr.sampled:
                return 200, encode_envelope(out)
            with tr.span("serialize"):
                payload = encode_envelope(out)
            return 200, payload
        except Exception as e:  # handlers answer their own errors; this
            # is the backstop for anything outside them
            log.error("backplane serve error", details=str(e))
            return 500, b""


# ----------------------------------------------------------------- client


class _Waiter:
    __slots__ = ("event", "status", "body")

    def __init__(self):
        self.event = threading.Event()
        self.status = 0
        self.body = b""


class BackplaneClient:
    """Frontend-side connection to the engine: one multiplexed UDS
    socket, a reader thread resolving verdicts by request id. Thread-
    safe; every HTTP handler thread calls `call()` concurrently.

    With `ring_mb` > 0 the client owns a shared-memory ring pair
    (control/shm.py): review bytes are written into the request ring
    and Q frames carry descriptors; responses come back as reply-ring
    descriptors resolved to zero-copy RingSlice payloads. The ring is
    an optimization with an always-available inline fallback — ring
    creation failure, a missing engine ack, an oversized review, or an
    exhausted ring all degrade to the original inline frames."""

    def __init__(self, socket_path: str, worker_id: str = "0",
                 connect_timeout: float = 1.0, ring_mb: float = 0.0,
                 ring_prefix: str = ""):
        self.socket_path = socket_path
        self.worker_id = worker_id
        self.connect_timeout = connect_timeout
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        # reentrant: _ensure_connected calls _drop() from inside its
        # own critical section when the engine dies between connect()
        # and the hello send (the chaos suite's SIGKILL window) — a
        # plain Lock self-deadlocks there, wedging every HTTP thread
        # of the frontend behind a lock nobody will ever release
        self._conn_lock = threading.RLock()
        self._pending: dict[int, _Waiter] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        # optional hooks the FrontendServer installs: per-forward ring
        # path counts ("ring"/"inline") and sampled ring_write stage
        # durations, both shipped engine-side over S frames
        self.stats_hook = None
        self.stage_hook = None
        self._rings = None
        self._ring_ok = threading.Event()
        if ring_mb > 0 and shm.supported():
            prefix = ring_prefix \
                or f"gk-bp-{os.getpid()}-{worker_id}"
            try:
                self._rings = shm.ClientRings(
                    prefix, max(1, int(ring_mb * 1024 * 1024)))
            except OSError as e:
                log.warning("shm ring unavailable; inline payloads",
                            details=str(e))

    # connection -----------------------------------------------------

    def _ensure_connected(self) -> socket.socket:
        sock = self._sock
        if sock is not None:
            return sock
        with self._conn_lock:
            if self._sock is not None:
                return self._sock
            if self._closed:
                raise BackplaneError("backplane client closed")
            try:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self.connect_timeout)
                sock.connect(self.socket_path)
                # per-op timeout: a wedged engine must unblock sendall
                # (the reader retries timeouts inside _recv_exact, so
                # an idle connection never desyncs)
                sock.settimeout(IO_TIMEOUT_S)
            except OSError as e:
                raise BackplaneError(
                    f"admission engine unreachable: {e}") from e
            self._sock = sock
            threading.Thread(target=self._read_loop, args=(sock,),
                             name="backplane-client-read",
                             daemon=True).start()
            hello = {"worker": self.worker_id}
            if self._rings is not None:
                # descriptors flow only after the engine's A-frame ack
                # confirms it attached this pair
                self._ring_ok.clear()
                hello["rings"] = self._rings.hello()
            try:
                _send_frame(sock, self._wlock, b"H",
                            jsonio.dumps_bytes(hello))
            except OSError as e:
                self._drop(sock)
                raise BackplaneError(
                    f"admission engine unreachable: {e}") from e
            return sock

    def _drop(self, sock: socket.socket) -> None:
        with self._conn_lock:
            current = self._sock is sock
            if current:
                self._sock = None
        try:
            sock.close()
        except OSError:
            pass
        if not current:
            # stale drop: the old reader thread unwinding AFTER the
            # sender already dropped (or replaced) this connection.
            # Its waiters were failed by the first drop — touching
            # the pending table again would kill requests riding the
            # replacement connection.
            return
        self._ring_ok.clear()
        # every in-flight request on the dead connection fails NOW —
        # the frontends answer per the failure stance instead of
        # letting HTTP callers hang into their own timeouts
        with self._pending_lock:
            waiters = list(self._pending.values())
            self._pending.clear()
        for w in waiters:
            w.status = -1
            w.event.set()
        if self._rings is not None:
            # the engine detached: free every outstanding request-ring
            # slot (their waiters just failed) so the ring cannot silt
            self._rings.on_disconnect()

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                (length,) = struct.unpack("!I", _recv_exact(sock, 4))
                payload = _recv_exact(sock, _check_frame_len(length))
                kind = payload[:1]
                if kind == b"R":
                    rid, status = _R_HEADER.unpack_from(payload, 1)
                    with self._pending_lock:
                        waiter = self._pending.pop(rid, None)
                    if waiter is not None:
                        waiter.status = status
                        waiter.body = payload[1 + _R_HEADER.size:]
                        waiter.event.set()
                elif kind == b"r":
                    # reply-ring descriptor: the payload never crossed
                    # the socket — hand the waiter a zero-copy slice it
                    # releases after the final HTTP send
                    rid, status, roff, rlen = _R_RING.unpack_from(
                        payload, 1)
                    rings = self._rings  # close() may null it mid-loop
                    with self._pending_lock:
                        waiter = self._pending.pop(rid, None)
                    if waiter is None:
                        # abandoned waiter (deadline fired): release
                        # the slot NOW or the reply ring silts up
                        if rings is not None:
                            rings.reply.release(roff)
                        continue
                    waiter.status = status
                    waiter.body = rings.reply_slice(roff, rlen) \
                        if rings is not None else b""
                    waiter.event.set()
                elif kind == b"A":
                    ack = jsonio.loads(payload[1:]) or {}
                    if ack.get("rings") and self._rings is not None:
                        self._ring_ok.set()
        except FrameDesyncError as e:
            log.error("backplane frame desync; dropping connection",
                      details=str(e))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            self._drop(sock)

    def connected(self) -> bool:
        return self._sock is not None

    def ensure_connected(self) -> None:
        """Eager connect (boot-time): lets the engine's connected-
        workers gauge reflect the plane before the first request."""
        self._ensure_connected()

    def inflight(self) -> int:
        """Requests forwarded and not yet answered — the router's
        least-load signal."""
        with self._pending_lock:
            return len(self._pending)

    def close(self) -> None:
        self._closed = True
        sock = self._sock
        if sock is not None:
            self._drop(sock)
        if self._rings is not None:
            rings, self._rings = self._rings, None
            rings.close(unlink_segments=True)

    def ring_fill(self) -> Optional[float]:
        """Request-ring used fraction (None when no ring) — shipped in
        the S-frame stats as the ring-sizing saturation read."""
        if self._rings is None:
            return None
        try:
            return self._rings.req.used_fraction()
        except (TypeError, ValueError):
            return None

    # calls ----------------------------------------------------------

    def call(self, path: str, body: bytes, timeout_s: float,
             deadline: float,
             trace_ctx: Optional[tuple] = None) -> tuple[int, bytes]:
        """Forward one review; returns (http_status, response_bytes).
        Raises BackplaneError when the engine is unreachable, the
        connection dies mid-flight, or no verdict lands by `deadline`
        (+ grace) — the caller answers per the failure stance.

        `trace_ctx` = (trace_id_hex, t_recv_monotonic) for a SAMPLED
        request: the span context rides the Q frame (t_fwd is stamped
        here, just before the send) so the engine reconstructs the
        frontend-side spans."""
        try:
            faults.fire("backplane.engine")
        except BackplaneError:
            raise
        except Exception as e:
            # an armed raise/error fault means "engine unreachable":
            # surface it as the typed error so the HTTP handler answers
            # per the failure stance instead of dropping the socket
            raise BackplaneError(f"injected engine fault: {e}") from e
        sock = self._ensure_connected()
        # ring write FIRST (before the waiter registers): the review
        # bytes land in the shared segment and only a ~40-byte
        # descriptor rides the socket. None (ring full / oversized /
        # unacked) falls back to the inline frame for THIS request.
        # Local ref: a concurrent close() nulls self._rings mid-call.
        rings = self._rings
        roff = None
        if rings is not None and self._ring_ok.is_set() \
                and not path.startswith(("/v1/preview",
                                         "/v1/auditslice")):
            t_w0 = time.monotonic()
            try:
                roff = rings.req.append(body)
            except (TypeError, ValueError):  # torn down concurrently
                roff = None
            if roff is not None and trace_ctx is not None \
                    and self.stage_hook is not None:
                self.stage_hook("ring_write", time.monotonic() - t_w0)
        if rings is not None and self.stats_hook is not None:
            self.stats_hook("ring" if roff is not None else "inline")
        # trace block built BEFORE the waiter registers: nothing
        # between registration and the send may raise anything but the
        # handled OSError, or the pending entry leaks forever
        flags = (TF_RING if roff is not None else 0) \
            | (TF_TRACE if trace_ctx is not None else 0)
        if trace_ctx is None:
            tblock = bytes((flags,))
        else:
            tid_hex, t_recv = trace_ctx
            tblock = bytes((flags,)) + _Q_TRACE.pack(
                bytes.fromhex(tid_hex)[:16].ljust(16, b"\x00"),
                t_recv, time.monotonic())
        tail = _Q_RING.pack(roff, len(body)) if roff is not None \
            else body
        waiter = _Waiter()
        with self._pending_lock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            rid = self._next_id
            self._pending[rid] = waiter
        try:
            _send_frame(sock, self._wlock, b"Q",
                        _Q_HEADER.pack(rid, timeout_s or 0.0), tblock,
                        _Q_PATHLEN.pack(len(path)), path.encode("ascii"),
                        tail)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(rid, None)
            if roff is not None:
                try:
                    rings.req.cancel(roff)
                except (TypeError, ValueError):
                    pass  # ring torn down concurrently
            self._drop(sock)
            raise BackplaneError(
                f"admission engine connection lost: {e}") from e
        # the engine's own deadline logic answers BEFORE the deadline;
        # the grace covers frame transit — expiry here means the engine
        # is gone or wedged
        if not waiter.event.wait(max(0.0, deadline - time.monotonic())
                                 + 0.5):
            with self._pending_lock:
                self._pending.pop(rid, None)
            if roff is not None:
                # nobody will consume the slot; free it (a wedged-but-
                # alive engine may later parse the reused bytes and
                # 400 a request id nobody waits on — harmless)
                try:
                    rings.req.cancel(roff)
                except (TypeError, ValueError):
                    pass  # ring torn down concurrently
            raise BackplaneError("admission engine verdict timed out")
        if waiter.status < 0:
            raise BackplaneError("admission engine connection lost")
        return waiter.status, waiter.body

    def send_stats(self, stats: dict) -> None:
        sock = self._sock
        if sock is None:
            return
        try:
            _send_frame(sock, self._wlock, b"S", jsonio.dumps_bytes(stats))
        except OSError:
            self._drop(sock)

    def _request_frame(self, kind: bytes, body: bytes,
                       timeout: float) -> tuple[int, bytes]:
        """One control round trip (L/M frames): send, wait on the
        shared waiter map. Raises BackplaneError on loss/timeout."""
        sock = self._ensure_connected()
        waiter = _Waiter()
        with self._pending_lock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            rid = self._next_id
            self._pending[rid] = waiter
        try:
            _send_frame(sock, self._wlock, kind, struct.pack("!I", rid),
                        body)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(rid, None)
            self._drop(sock)
            raise BackplaneError(
                f"engine connection lost: {e}") from e
        if not waiter.event.wait(timeout):
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise BackplaneError("engine control call timed out")
        if waiter.status < 0:
            raise BackplaneError("engine connection lost")
        return waiter.status, waiter.body

    def control(self, op: dict, timeout: float = 30.0) -> None:
        """Replicate one library op to this engine (primary-side).
        Raises BackplaneError when the op did not land — the caller
        marks the engine dirty and resyncs."""
        status, body = self._request_frame(
            b"L", jsonio.dumps_bytes(op), timeout)
        if status != 200:
            raise BackplaneError(
                f"library op refused ({status}): "
                f"{body.decode('utf-8', 'replace')[:200]}")

    def poll_stats(self, timeout: float = 10.0) -> dict:
        """Fetch this engine's relayed-metrics snapshot (M frame)."""
        status, body = self._request_frame(b"M", b"", timeout)
        if status != 200:
            raise BackplaneError(f"stats poll refused ({status})")
        try:
            return jsonio.loads(body) or {}
        except ValueError as e:
            raise BackplaneError(f"stats poll unparseable: {e}") from e

    def review_bulk(self, payloads: list, timeout_s: float = 30.0
                    ) -> list[bytes]:
        """STREAMING binary ingest: ship a whole batch of serialized
        AdmissionReviews as one length-prefixed B frame (no HTTP/1.1
        framing, no per-review frames) and get the envelope bytes back
        in order. The engine parses once and feeds the MicroBatcher in
        one enqueue pass — the bulk-caller path for CI scanners and
        service-mesh authorizers. Raises BackplaneError on loss or
        timeout."""
        return self.review_bulk_finish(
            self.review_bulk_begin(payloads, timeout_s))

    def review_bulk_begin(self, payloads: list,
                          timeout_s: float = 30.0) -> tuple:
        """Send one B frame and return immediately with a ticket for
        `review_bulk_finish` — the pipelining half of `review_bulk`.
        Bulk callers (the fleet scanner) keep K frames in flight so
        the next batch encodes host-side while this one evaluates in
        the engine; the frame-id/waiter plumbing already multiplexes
        replies, so depth costs no thread per in-flight frame."""
        sock = self._ensure_connected()
        waiter = _Waiter()
        with self._pending_lock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            rid = self._next_id
            self._pending[rid] = waiter
        parts = [_B_HEADER.pack(rid, timeout_s or 0.0, len(payloads))]
        for b in payloads:
            parts.append(_B_LEN.pack(len(b)))
            parts.append(b)
        try:
            _send_frame(sock, self._wlock, b"B", *parts)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(rid, None)
            self._drop(sock)
            raise BackplaneError(
                f"bulk ingest connection lost: {e}") from e
        return (rid, waiter, timeout_s)

    def review_bulk_finish(self, ticket: tuple) -> list[bytes]:
        """Wait out one `review_bulk_begin` ticket and parse its
        reply. Raises BackplaneError on loss or timeout."""
        rid, waiter, timeout_s = ticket
        if not waiter.event.wait((timeout_s or 30.0) + 5.0):
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise BackplaneError("bulk ingest timed out")
        if waiter.status < 0:
            raise BackplaneError("bulk ingest connection lost")
        if waiter.status != 200:
            raise BackplaneError(
                f"bulk ingest refused ({waiter.status}): "
                f"{bytes(waiter.body)[:200].decode('utf-8', 'replace')}")
        body = bytes(waiter.body)
        (count,) = _B_LEN.unpack_from(body, 0)
        off = _B_LEN.size
        outs = []
        for _ in range(count):
            (n,) = _B_LEN.unpack_from(body, off)
            off += _B_LEN.size
            outs.append(body[off:off + n])
            off += n
        return outs


# ----------------------------------------------------------------- router


class BackplaneRouter:
    """Frontend-side fan-in over N engine sockets: one multiplexed
    BackplaneClient per engine. Routing: least in-flight forwards
    first; ties break on the request hash (stable spread under equal
    load); an engine that fails mid-call (died, wedged, unreachable)
    fails over to the next-best engine — each tried at most once — so
    one killed engine costs its in-flight requests one retry, not a
    stance answer, and the burst keeps completing on the survivors.

    Drop-in for BackplaneClient where the FrontendServer is concerned
    (call / send_stats / connected / close)."""

    def __init__(self, socket_paths, worker_id: str = "0",
                 connect_timeout: float = 1.0, ring_mb: float = 0.0,
                 ring_prefix: str = ""):
        paths = list(socket_paths)
        if not paths:
            raise ValueError("router needs at least one engine socket")
        # one ring pair per ENGINE connection (each engine process maps
        # its own pair); names stay unique per (worker, engine index)
        base = ring_prefix or f"gk-bp-{os.getpid()}-{worker_id}"
        self.clients = [BackplaneClient(p, worker_id=worker_id,
                                        connect_timeout=connect_timeout,
                                        ring_mb=ring_mb,
                                        ring_prefix=f"{base}-e{i}")
                        for i, p in enumerate(paths)]

    @property
    def stats_hook(self):
        return self.clients[0].stats_hook

    @stats_hook.setter
    def stats_hook(self, fn) -> None:
        for c in self.clients:
            c.stats_hook = fn

    @property
    def stage_hook(self):
        return self.clients[0].stage_hook

    @stage_hook.setter
    def stage_hook(self, fn) -> None:
        for c in self.clients:
            c.stage_hook = fn

    def ring_fill(self) -> Optional[float]:
        fills = [f for f in (c.ring_fill() for c in self.clients)
                 if f is not None]
        return max(fills) if fills else None

    def connected(self) -> bool:
        return any(c.connected() for c in self.clients)

    def ensure_connected(self) -> None:
        for c in self.clients:
            try:
                c.ensure_connected()
            except BackplaneError:
                pass  # that engine retries lazily on first forward

    def close(self) -> None:
        for c in self.clients:
            c.close()

    def inflight(self) -> int:
        return sum(c.inflight() for c in self.clients)

    def call(self, path: str, body: bytes, timeout_s: float,
             deadline: float,
             trace_ctx: Optional[tuple] = None) -> tuple[int, bytes]:
        clients = self.clients
        if path.startswith("/v1/preview"):
            # previews pin to the PRIMARY (engine 0): it owns the live
            # tracker-fed inventory; pinned engine children only hold
            # sync-time snapshots. No failover — a preview is not an
            # admission verdict, an error answer is fine.
            status, out = clients[0].call(path, body, timeout_s,
                                          deadline, trace_ctx=trace_ctx)
            if status == STATUS_NOT_READY:
                raise BackplaneError("engine awaiting library sync")
            return status, out
        if len(clients) == 1:
            status, out = clients[0].call(path, body, timeout_s,
                                          deadline,
                                          trace_ctx=trace_ctx)
            if status == STATUS_NOT_READY:
                # no synced engine to fail over to: the frontend
                # answers per the failure stance
                raise BackplaneError("engine awaiting library sync")
            return status, out
        import zlib

        h = zlib.crc32(body) % len(clients)
        order = sorted(range(len(clients)),
                       key=lambda k: (clients[k].inflight(),
                                      (k - h) % len(clients)))
        err: Optional[BackplaneError] = None
        for k in order:
            try:
                status, out = clients[k].call(path, body, timeout_s,
                                              deadline,
                                              trace_ctx=trace_ctx)
            except BackplaneError as e:
                err = e  # next engine; the burst must not drop
                continue
            if status == STATUS_NOT_READY:
                # a respawned engine awaiting its library sync: a
                # synced engine must answer instead
                err = BackplaneError("engine awaiting library sync")
                continue
            return status, out
        raise err if err is not None else BackplaneError("no engines")

    def send_stats(self, stats: dict) -> None:
        # stats go to the PRIMARY engine (index 0 — the process whose
        # registry is scraped); fall back to any connected engine so a
        # dead primary does not silently eat the deltas forever
        for c in self.clients:
            if c.connected():
                c.send_stats(stats)
                return


# --------------------------------------------------------------- frontend


class _StatsAccumulator:
    """Forward-latency histogram + failure-stance counter + per-stage
    span-duration histograms (sampled requests only), accumulated
    locally and shipped to the engine as periodic deltas."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(STATS_BUCKETS) + 1)
        self._sum = 0.0
        self._n = 0
        self._errors = 0
        # stage -> [bucket_counts, sum, n] over metrics.STAGE_BUCKETS:
        # the frontend-side spans of SAMPLED requests, merged into
        # gatekeeper_tpu_stage_duration_seconds engine-side
        self._stages: dict[str, list] = {}
        # shm-ring path counts ("ring" forwarded as a descriptor,
        # "inline" fell back) -> gatekeeper_tpu_backplane_ring_total
        self._ring: dict[str, int] = {}

    def observe(self, seconds: float) -> None:
        with self._lock:
            _bucket_observe(self._counts, STATS_BUCKETS, seconds)
            self._sum += seconds
            self._n += 1

    def observe_stage(self, stage: str, seconds: float) -> None:
        with self._lock:
            ent = self._stages.get(stage)
            if ent is None:
                ent = self._stages[stage] = [
                    [0] * (len(STAGE_BUCKETS) + 1), 0.0, 0]
            _bucket_observe(ent[0], STAGE_BUCKETS, seconds)
            ent[1] += seconds
            ent[2] += 1

    def error(self) -> None:
        with self._lock:
            self._errors += 1

    def ring_path(self, path: str) -> None:
        with self._lock:
            self._ring[path] = self._ring.get(path, 0) + 1

    def drain(self, worker: str) -> Optional[dict]:
        with self._lock:
            if not self._n and not self._errors and not self._stages \
                    and not self._ring:
                return None
            out = {"worker": worker, "buckets": self._counts,
                   "sum": round(self._sum, 6), "count": self._n,
                   "errors": self._errors}
            if self._stages:
                out["stages"] = {
                    stage: {"buckets": ent[0],
                            "sum": round(ent[1], 6), "count": ent[2]}
                    for stage, ent in self._stages.items()}
                self._stages = {}
            if self._ring:
                out["ring_paths"] = self._ring
                self._ring = {}
            self._counts = [0] * (len(STATS_BUCKETS) + 1)
            self._sum = 0.0
            self._n = 0
            self._errors = 0
            return out


class FrontendServer:
    """One pre-forked HTTP frontend: accept + TLS + header parse, then
    forward the body bytes over the backplane. Never JSON-decodes a
    review on the hot path (the failure stance parses lazily, only to
    recover the uid)."""

    def __init__(self, client: BackplaneClient, port: int = 8443,
                 addr: str = "", certfile: Optional[str] = None,
                 keyfile: Optional[str] = None, reuse_port: bool = True,
                 serve: tuple = ("admit", "admitlabel", "mutate"),
                 fail_closed: bool = False,
                 mutation_fail_closed: Optional[bool] = None,
                 default_timeout: float = DEFAULT_WEBHOOK_TIMEOUT_S,
                 worker_id: str = "0"):
        from .webhook import FastHTTPServer

        self.client = client
        self.serve = frozenset(serve)
        self.fail_closed = fail_closed
        self.mutation_fail_closed = (fail_closed if mutation_fail_closed
                                     is None else mutation_fail_closed)
        self.default_timeout = default_timeout
        self.worker_id = worker_id
        self.stats = _StatsAccumulator()
        # the client reports ring-path usage and sampled ring_write
        # durations into this frontend's stats accumulator (both ride
        # the S-frame deltas to the engine's registry)
        client.stats_hook = self.stats.ring_path
        client.stage_hook = self.stats.observe_stage
        self.http = FastHTTPServer((addr, port), self._dispatch,
                                   reuse_port=reuse_port,
                                   certfile=certfile, keyfile=keyfile)
        self.server = self.http.server
        self.port = self.http.port
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="frontend", daemon=True)
        self._last_inflight = 0
        self._stats_stop = threading.Event()
        self._stats_thread = threading.Thread(
            target=self._stats_loop, name="frontend-stats", daemon=True)

    # request path ---------------------------------------------------

    def _route(self, path: str) -> Optional[str]:
        route = route_path(path)
        return route if route in self.serve else None

    def _dispatch(self, path: str, body: bytes,
                  traceparent: Optional[str] = None) -> tuple:
        t_recv = time.monotonic()
        route = self._route(path)
        if route is None:
            # un-served endpoints 404 LOCALLY: no backplane hop for an
            # operation the operator turned off
            return 404, b""
        # the frontend is the sampling edge: it decides, forwards the
        # span context over the Q frame, and answers X-Trace-Id. The
        # engine owns the flight recorder; the frontend only ships its
        # own two stages as aggregated S-frame deltas.
        tid = gtrace.TRACER.sample_context(traceparent)
        timeout_s = parse_timeout_query(path.partition("?")[2]) or 0.0
        if route == "preview":
            # a cold preview may legitimately wait out an XLA compile;
            # its wait is its own, not an admission budget
            deadline = time.monotonic() + (timeout_s or 300.0)
        elif timeout_s > 0:
            deadline = request_deadline({"timeoutSeconds": timeout_s},
                                        self.default_timeout)
        else:
            # no query budget: the frontend cannot see a body-carried
            # timeoutSeconds without parsing, so its WAIT is only a
            # backstop at the API server's maximum webhook budget — the
            # engine enforces the real (possibly longer-than-default)
            # deadline and answers per stance before it
            deadline = time.monotonic() + MAX_WEBHOOK_TIMEOUT_S
        t0 = time.monotonic()
        try:
            status, payload = self.client.call(
                path, body, timeout_s, deadline,
                trace_ctx=None if tid is None else (tid, t_recv))
            now = time.monotonic()
            self.stats.observe(now - t0)
            if tid is None:
                return status, payload
            # ship ONLY the stage this process truly owns: the forward
            # hop and every engine stage are timed (and histogrammed)
            # engine-side — shipping the call round trip as a stage
            # would re-count all of them under one label
            self.stats.observe_stage("frontend_parse", t0 - t_recv)
            return status, payload, {"X-Trace-Id": tid}
        except BackplaneError as e:
            self.stats.error()
            if route == "preview":
                # not an admission verdict: a plain error, no stance
                out = 503, jsonio.dumps_bytes({"error": str(e)})
            else:
                out = 200, self._stance_envelope(route, body, str(e))
            # a stance answer still reports its trace id: the id is in
            # the caller's hands (and logs) even though the engine
            # never saw the request
            return out if tid is None else (*out, {"X-Trace-Id": tid})

    def _stance_envelope(self, route: str, body: bytes,
                         message: str) -> bytes:
        """The failure-stance verdict a frontend issues on its own when
        the engine cannot: fail-open allows with a warning status,
        fail-closed denies. Parses the review ONLY here, to echo uid
        and envelope apiVersion/kind."""
        uid = ""
        api_version = kind = None
        try:
            review = jsonio.loads(body)
            if isinstance(review, dict):
                uid = (review.get("request") or {}).get("uid") or ""
                api_version = review.get("apiVersion")
                kind = review.get("kind")
        except ValueError:
            pass
        fail_closed = (self.mutation_fail_closed if route == "mutate"
                       else self.fail_closed)
        return encode_envelope({
            "apiVersion": api_version or "admission.k8s.io/v1beta1",
            "kind": kind or "AdmissionReview",
            "response": {"uid": uid, "allowed": not fail_closed,
                         "status": {"code": 503, "message": message}},
        })

    # lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread.start()
        self._stats_thread.start()

    def _stats_loop(self) -> None:
        while not self._stats_stop.wait(STATS_INTERVAL_S):
            stats = self.stats.drain(self.worker_id)
            inflight = self.client.inflight()
            if stats is None:
                if not inflight and self._last_inflight == 0:
                    continue  # nothing moved; skip the frame
                stats = {"worker": self.worker_id}
            stats["inflight"] = inflight
            self._last_inflight = inflight
            fill = getattr(self.client, "ring_fill", lambda: None)()
            if fill is not None:
                stats["ring_fill"] = round(fill, 4)
            self.client.send_stats(stats)

    def stop(self, drain_timeout: float = 10.0) -> None:
        """Frontend drain: stop accepting, finish in-flight HTTP
        requests (their verdicts are already in flight on the
        backplane), close."""
        self.server.shutdown()
        end = time.monotonic() + drain_timeout
        while time.monotonic() < end:
            if self.http.inflight() == 0:
                break
            time.sleep(0.02)
        self._stats_stop.set()
        self.client.close()
        self.server.server_close()


# ------------------------------------------------------------- supervisor


class FrontendSupervisor:
    """Pre-forks N frontend processes (this module's __main__), binds
    them all to one SO_REUSEPORT port, respawns crashed children, and
    drains them BEFORE the engine on shutdown."""

    def __init__(self, n: int, socket_path, port: int = 8443,
                 addr: str = "", certfile: Optional[str] = None,
                 keyfile: Optional[str] = None,
                 serve: tuple = ("admit", "admitlabel", "mutate"),
                 fail_closed: bool = False,
                 mutation_fail_closed: Optional[bool] = None,
                 default_timeout: float = DEFAULT_WEBHOOK_TIMEOUT_S,
                 ready_timeout: float = 30.0,
                 trace_sample_rate: float = 0.0,
                 shm_ring_mb: float = 8.0):
        self.n = n
        self.trace_sample_rate = trace_sample_rate
        # shared-memory ring size per frontend (MB); 0 disables the
        # rings and every review rides inline frames
        self.shm_ring_mb = shm_ring_mb
        # one socket (single engine) or a list (the N-engine plane:
        # each frontend connects to every engine and routes)
        if not isinstance(socket_path, str):
            socket_path = ",".join(socket_path)
        self.socket_path = socket_path
        self.addr = addr
        self.certfile = certfile
        self.keyfile = keyfile
        self.serve = tuple(serve)
        self.fail_closed = fail_closed
        self.mutation_fail_closed = mutation_fail_closed
        self.default_timeout = default_timeout
        self.ready_timeout = ready_timeout
        self.port = port
        self._holder: Optional[socket.socket] = None
        if port == 0:
            # ephemeral port: hold a bound (non-listening) SO_REUSEPORT
            # socket so the chosen port survives until every child has
            # bound it; the kernel only balances across LISTENING
            # sockets, so the placeholder never receives connections
            self._holder = socket.socket()
            self._holder.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEPORT, 1)
            self._holder.bind((addr or "127.0.0.1", 0))
            self.port = self._holder.getsockname()[1]
        self._procs: list[Optional[subprocess.Popen]] = [None] * n
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # gray-failure liveness: frontends print an HB line on stdout
        # every second (frontend_main); the per-slot reader thread
        # stamps arrival times here and the monitor loop declares a
        # child WEDGED — alive but silent past the deadline — and
        # SIGKILLs it onto the ordinary respawn path. Death-only
        # detection (waitpid) misses a SIGSTOP'd/hung frontend, which
        # holds its SO_REUSEPORT share and blackholes its connections.
        self.heartbeat_deadline_s = 10.0
        self._hb: dict[int, float] = {}
        # crash-loop rate limiting + MTTR accounting
        self._backoff = liveness.Backoff("frontend")
        self._spawned_at: dict[int, float] = {}
        self._respawn_at: dict[int, float] = {}
        self._detected: dict[int, tuple] = {}  # k -> (t_detect, fault)

    def _ring_prefix(self, k: int) -> str:
        # deterministic per worker SLOT (not per child pid): the
        # supervisor can sweep a SIGKILLed child's stale segments
        # before handing the name to its replacement
        return f"gk-bp-{os.getpid()}-w{k}"

    def _sweep_rings(self, k: int) -> None:
        prefix = self._ring_prefix(k)
        shm.sweep_stale(prefix)
        # router mode: one ring pair per engine connection
        for i in range(len(self.socket_path.split(","))):
            shm.sweep_stale(f"{prefix}-e{i}")

    def _spawn(self, k: int) -> subprocess.Popen:
        self._sweep_rings(k)
        cmd = [sys.executable, "-m", "gatekeeper_tpu.control.backplane",
               "--socket", self.socket_path,
               "--port", str(self.port),
               "--addr", self.addr,
               "--worker-id", str(k),
               "--serve", ",".join(self.serve),
               "--default-timeout", str(self.default_timeout),
               "--trace-sample-rate", str(self.trace_sample_rate),
               "--shm-ring-mb", str(self.shm_ring_mb),
               "--shm-ring-name", self._ring_prefix(k)]
        if self.certfile:
            cmd += ["--certfile", self.certfile]
            if self.keyfile:
                cmd += ["--keyfile", self.keyfile]
        if self.fail_closed:
            cmd += ["--fail-closed"]
        if self.mutation_fail_closed is not None:
            # explicit true/false: collapsing False into "unset" would
            # make the frontend inherit the VALIDATING stance for
            # mutations, flipping an operator's fail-open override
            cmd += ["--mutation-fail-closed",
                    "true" if self.mutation_fail_closed else "false"]
        return subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)

    def start(self) -> None:
        try:
            for k in range(self.n):
                self._procs[k] = self._spawn(k)
            deadline = time.monotonic() + self.ready_timeout
            for k, proc in enumerate(self._procs):
                self._await_ready(k, proc, deadline)
        except Exception:
            # a worker that never came up must not leak its siblings
            self._stopping.set()
            for proc in self._procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
            raise
        if self._holder is not None:
            self._holder.close()
            self._holder = None
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="frontend-supervisor",
                                         daemon=True)
        self._monitor.start()
        log.info("admission frontends serving",
                 details={"workers": self.n, "port": self.port})

    def _await_ready(self, k: int, proc: subprocess.Popen,
                     deadline: float) -> None:
        line: list = []

        def read():
            line.append(proc.stdout.readline())

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(max(0.1, deadline - time.monotonic()))
        if not line or "READY" not in (line[0] or ""):
            raise RuntimeError(
                f"admission frontend {k} failed to start")
        # reader thread for the child's remaining stdout: keeps the
        # pipe from ever filling (the old full-read drain's job) AND
        # stamps every line — the 1/s HB lines above all — as this
        # slot's liveness heartbeat
        self._hb[k] = time.monotonic()
        self._spawned_at[k] = time.monotonic()
        threading.Thread(target=self._pump_heartbeats, args=(k, proc),
                         daemon=True).start()

    def _pump_heartbeats(self, k: int, proc: subprocess.Popen) -> None:
        try:
            for _ in proc.stdout:
                self._hb[k] = time.monotonic()
        except (OSError, ValueError):
            pass  # child died / pipe closed: poll() takes it from here

    def _monitor_loop(self) -> None:
        while not self._stopping.wait(0.5):
            now = time.monotonic()
            for k, proc in enumerate(self._procs):
                if proc is None or self._stopping.is_set():
                    continue
                dead = proc.poll() is not None
                if not dead and k not in self._detected \
                        and now - self._hb.get(k, now) \
                        > self.heartbeat_deadline_s:
                    # gray failure: the process is alive but has not
                    # written a heartbeat past the deadline (SIGSTOP,
                    # hung accept loop). SIGKILL it; the respawn path
                    # below heals it like any crash.
                    log.warning(
                        "admission frontend wedged (no heartbeat); "
                        "killing",
                        details={"worker": k,
                                 "hb_age_s":
                                     round(now - self._hb[k], 2)})
                    self._detected[k] = (now, "wedge")
                    try:
                        proc.kill()
                        proc.wait(5.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                    dead = proc.poll() is not None
                if not dead:
                    if self._backoff.pending(k) \
                            and now - self._spawned_at.get(k, now) \
                            >= self._backoff.healthy_after:
                        self._backoff.note_healthy(k)
                    continue
                if k not in self._detected:
                    log.warning("admission frontend died; respawning",
                                details={"worker": k,
                                         "rc": proc.returncode})
                    self._detected[k] = (now, "death")
                    uptime = now - self._spawned_at.get(k, now)
                    delay = self._backoff.delay_for(k, uptime)
                    self._respawn_at[k] = now + delay
                if now < self._respawn_at.get(k, now):
                    continue  # holding the crash-loop backoff delay
                p = None
                try:
                    p = self._spawn(k)
                    self._await_ready(
                        k, p, time.monotonic() + self.ready_timeout)
                    self._procs[k] = p
                    self._backoff.respawned(k)
                    self._respawn_at.pop(k, None)
                    t0, fault = self._detected.pop(k, (now, "death"))
                    from . import metrics as _metrics
                    _metrics.report_fault_recovery(
                        "frontend", fault, time.monotonic() - t0)
                except Exception as e:
                    log.error("frontend respawn failed",
                              details={"worker": k, "error": str(e)})
                    # never leak a half-started child: it may hold
                    # the SO_REUSEPORT bind and receive live
                    # connections while untracked
                    if p is not None:
                        try:
                            p.kill()
                        except OSError:
                            pass
                    # the failed attempt counts as another fast death
                    # for the backoff ladder
                    self._respawn_at[k] = time.monotonic() + \
                        self._backoff.delay_for(k, 0.0)

    def alive(self) -> bool:
        return all(p is not None and p.poll() is None
                   for p in self._procs)

    # chaos hooks ----------------------------------------------------

    def child_pids(self) -> dict[int, int]:
        """Live child pids by worker slot (the chaos verifier's
        process-leak baseline)."""
        return {k: p.pid for k, p in enumerate(self._procs)
                if p is not None and p.poll() is None}

    def kill_child(self, k: int) -> None:
        """Chaos hook: SIGKILL one frontend (the monitor respawns it;
        the kernel re-balances its SO_REUSEPORT share meanwhile)."""
        proc = self._procs[k] if 0 <= k < len(self._procs) else None
        if proc is not None and proc.poll() is None:
            proc.kill()

    def pause_child(self, k: int) -> None:
        """Chaos hook: SIGSTOP one frontend — alive to waitpid, silent
        on the wire. Only the heartbeat deadline can catch this."""
        proc = self._procs[k] if 0 <= k < len(self._procs) else None
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGSTOP)

    def resume_child(self, k: int) -> None:
        proc = self._procs[k] if 0 <= k < len(self._procs) else None
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGCONT)

    def stop(self, timeout: float = 15.0) -> None:
        """SIGTERM every frontend (each drains its in-flight HTTP
        requests) and wait — the engine drains only after this
        returns."""
        self._stopping.set()
        for proc in self._procs:
            if proc is not None and proc.poll() is None:
                try:
                    # a SIGSTOP'd child (chaos pause) cannot handle
                    # SIGTERM while stopped: resume it first so it can
                    # drain; SIGCONT on a running child is a no-op
                    os.kill(proc.pid, signal.SIGCONT)
                    proc.terminate()
                except OSError:
                    pass
        end = time.monotonic() + timeout
        for proc in self._procs:
            if proc is None:
                continue
            try:
                proc.wait(max(0.1, end - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                try:  # reap: an unwaited kill leaves a zombie that
                    proc.wait(5.0)  # still answers os.kill(pid, 0)
                except subprocess.TimeoutExpired:
                    pass
        if self._holder is not None:
            self._holder.close()
            self._holder = None
        self._backoff.close()
        # a gracefully-exited frontend unlinked its own rings; sweep
        # anyway so a kill -9'd child cannot leak /dev/shm segments
        for k in range(self.n):
            self._sweep_rings(k)


# ------------------------------------------------------ engine supervisor


class EngineSupervisor:
    """Spawns the N-1 admission ENGINE child processes of the N-engine
    plane (engine 0 stays in the primary process), one per chip —
    `python -m gatekeeper_tpu.control.engine --engine-id k --device k`
    — monitors and respawns them, replicates every library mutation to
    each over L frames (a freshly (re)spawned or replication-failed
    engine gets a FULL sync first), and polls per-engine metric totals
    over M frames, merging the deltas into this process's registry so
    shed accounting / decision counts / cache outcomes stay global on
    the primary's /metrics."""

    POLL_INTERVAL_S = 2.0
    # labels for the recovery histogram / backoff gauges; the audit
    # subclass overrides both
    RECOVERY_COMPONENT = "engine"
    SUPERVISOR_LABEL = "engine"

    def __init__(self, engine_ids, socket_for, spawn_args=(),
                 snapshot_provider=None, ready_timeout: float = 180.0,
                 heartbeat_deadline_s: float = 10.0):
        self.engine_ids = list(engine_ids)
        self.socket_for = socket_for          # engine id -> socket path
        self.spawn_args = list(spawn_args)    # passthrough CLI flags
        self.snapshot_provider = snapshot_provider  # () -> full sync op
        self.ready_timeout = ready_timeout
        self._procs: dict[int, Optional[subprocess.Popen]] = \
            {k: None for k in self.engine_ids}
        self._ctl: dict[int, BackplaneClient] = {}
        self._dirty: dict[int, bool] = {k: True for k in self.engine_ids}
        self._prev_stats: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # gray-failure liveness: the M-frame stats poll doubles as the
        # heartbeat — a child that is alive to waitpid but has not
        # ANSWERED a poll (or resync) within this deadline is wedged
        # (SIGSTOP, spinning, hung device) and gets SIGKILLed onto the
        # ordinary respawn+resync path. Must comfortably exceed
        # POLL_INTERVAL_S plus the poll timeout.
        self.heartbeat_deadline_s = heartbeat_deadline_s
        self._last_ok: dict[int, float] = {}
        # stamped before each poll; a child is only wedged if a poll
        # was ATTEMPTED after its last answer — polls are serialized,
        # so one wedged sibling stalling its 5 s poll timeout must not
        # age a healthy (simply not-yet-re-polled) child past the
        # deadline and get it falsely killed
        self._last_attempt: dict[int, float] = {}
        # crash-loop rate limiting + MTTR accounting
        self._backoff = liveness.Backoff(self.SUPERVISOR_LABEL)
        self._spawned_at: dict[int, float] = {}
        self._respawn_at: dict[int, float] = {}
        self._detected: dict[int, tuple] = {}  # k -> (t_detect, fault)
        # fan-out actuation (adaptive controller): how many children
        # should be RUNNING. Children beyond the prefix are "parked" —
        # terminated and not respawned until the count rises again.
        # The configured engine_ids list stays the hard ceiling.
        self._desired_children = len(self.engine_ids)
        # serving-knob replication: the latest set_knobs() payload and
        # a generation counter; the monitor loop pushes it to every
        # synced child and re-pushes after each respawn/resync
        self._knobs: Optional[dict] = None
        self._knobs_gen = 0
        self._knobs_pushed: dict[int, int] = {}

    # spawn / readiness ----------------------------------------------

    def engine_label(self, k: int) -> str:
        """The `engine=` label this child relays its stats under; gauge
        zeroing on park/death/stop must target the SAME string or a
        dead child's duty/depth series outlives it."""
        return str(k)

    def _spawn(self, k: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "gatekeeper_tpu.control.engine",
               "--socket", self.socket_for(k),
               "--engine-id", str(k),
               "--device", str(k)] + self.spawn_args
        return subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)

    def _await_ready(self, k: int, proc: subprocess.Popen,
                     deadline: float) -> None:
        line: list = []

        def read():
            line.append(proc.stdout.readline())

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(max(0.1, deadline - time.monotonic()))
        if not line or "READY" not in (line[0] or ""):
            raise RuntimeError(f"admission engine {k} failed to start")
        # liveness stamps: the child just proved it can talk; the
        # heartbeat deadline measures from here until its first
        # answered poll
        self._spawned_at[k] = self._last_ok[k] = time.monotonic()
        threading.Thread(target=lambda: proc.stdout.read(),
                         daemon=True).start()

    def start(self) -> None:
        try:
            deadline = time.monotonic() + self.ready_timeout
            for k in self.engine_ids:
                self._procs[k] = self._spawn(k)
            for k in self.engine_ids:
                self._await_ready(k, self._procs[k], deadline)
        except Exception:
            self._stopping.set()
            for proc in self._procs.values():
                if proc is not None and proc.poll() is None:
                    proc.kill()
            raise
        for k in self.engine_ids:
            self._ctl[k] = BackplaneClient(self.socket_for(k),
                                           worker_id=f"ctl-{k}",
                                           connect_timeout=5.0)
            self._resync(k)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="engine-supervisor",
                                         daemon=True)
        self._monitor.start()
        log.info("admission engines serving",
                 details={"engines": 1 + len(self.engine_ids)})

    # library replication --------------------------------------------

    def _resync(self, k: int) -> None:
        """Full library sync to one engine (boot, respawn, or heal
        after a failed incremental op). A sync that itself fails keeps
        the engine dirty; the monitor loop retries.

        The lock makes (clear dirty, snapshot, send) atomic with
        respect to replicate(): without it, an op landing between the
        snapshot and the sync SEND could replicate first and then be
        REMOVED by the sync's drop-extras reconciliation (built from
        the pre-op snapshot) — a permanently lost mutation on that
        engine. Under the lock every racing op sends after the sync
        frame on the ordered control stream, so it re-applies; an op
        the snapshot already caught applies twice, which the clients'
        semantic-equal dedupe absorbs."""
        provider = self.snapshot_provider
        if provider is None:
            self._dirty[k] = False
            return
        with self._lock:
            self._dirty[k] = False
            try:
                op = provider()
                op["op"] = "sync"
                self._ctl[k].control(op, timeout=120.0)
                self._last_ok[k] = time.monotonic()
                log.info("engine resynced", details={"engine": k})
            except Exception as e:
                self._dirty[k] = True
                log.warning("engine resync failed; will retry",
                            details={"engine": k, "error": str(e)})

    def replicate(self, op: str, obj) -> None:
        """Fan one library mutation out to every engine child (the
        primary's own client already applied it). Called from the
        Client's on_change observer — failures mark the engine dirty
        for a monitor-loop resync, they never raise into ingestion.
        Serialized against _resync by the lock (see there)."""
        msg = {"op": op, "obj": obj}
        with self._lock:
            for k in self.engine_ids:
                ctl = self._ctl.get(k)
                if ctl is None or self._dirty.get(k):
                    continue  # resync (which includes this op) pending
                try:
                    ctl.control(msg)
                except BackplaneError as e:
                    self._dirty[k] = True
                    log.warning("library replication failed; engine "
                                "marked for resync",
                                details={"engine": k, "error": str(e)})

    # fan-out / knob actuation ---------------------------------------

    def scale_to(self, total: int) -> int:
        """Desired TOTAL engine count, primary included (the adaptive
        controller's fan-out actuator). Clamped to [1, configured].
        NON-BLOCKING: this only records the target — the monitor loop
        parks (terminates, stops respawning) children beyond it and
        unparks (respawns + resyncs) them when it rises. Returns the
        clamped total."""
        want = min(1 + len(self.engine_ids), max(1, int(total)))
        self._desired_children = want - 1
        return want

    def active_total(self) -> int:
        """Desired total engine count (primary + unparked children)."""
        return 1 + self._desired_children

    def _active_ids(self) -> set:
        return set(self.engine_ids[: self._desired_children])

    def set_knobs(self, knobs: dict) -> None:
        """Queue a serving-knob update (MicroBatcher max_wait /
        max_batch / max_queue share) for every engine child.
        NON-BLOCKING: the monitor loop pushes the newest payload over
        each child's control stream, and re-pushes after any respawn,
        so a healed engine never serves with stale knobs."""
        with self._lock:
            self._knobs = dict(knobs)
            self._knobs_gen += 1

    def _push_knobs(self) -> None:
        """Send the newest knob payload to synced children that have
        not acknowledged this generation. A send failure just leaves
        the child un-acked for the next pass — knob pushes are
        idempotent, unlike library ops, so no dirty/resync machinery."""
        with self._lock:
            knobs, gen = self._knobs, self._knobs_gen
        if knobs is None:
            return
        for k in self.engine_ids:
            if self._knobs_pushed.get(k) == gen:
                continue
            ctl = self._ctl.get(k)
            if ctl is None or self._dirty.get(k):
                continue
            try:
                ctl.control({"op": "knobs", "obj": knobs})
                self._knobs_pushed[k] = gen
            except BackplaneError as e:
                log.warning("knob replication failed; will retry",
                            details={"engine": k, "error": str(e)})

    # monitor / stats ------------------------------------------------

    def _monitor_loop(self) -> None:
        last_poll = 0.0
        while not self._stopping.wait(0.5):
            active = self._active_ids()
            now = time.monotonic()
            # gray-failure pass: a child that is ALIVE to waitpid but
            # has not answered a stats poll or resync within the
            # heartbeat deadline is WEDGED (SIGSTOP'd, spinning, hung
            # on its device) — death-only detection would leave it
            # holding its socket while frontends pile failovers onto
            # survivors. SIGKILL it; the respawn pass below heals it
            # like any crash.
            for k in self.engine_ids:
                if k not in active or k in self._detected:
                    continue
                proc = self._procs.get(k)
                if proc is None or proc.poll() is not None:
                    continue
                last_ok = self._last_ok.get(k, now)
                age = now - last_ok
                if age > self.heartbeat_deadline_s \
                        and self._last_attempt.get(k, 0.0) > last_ok:
                    log.warning(
                        "engine child wedged (no poll answer); "
                        "killing",
                        details={"engine": k,
                                 "supervisor": self.SUPERVISOR_LABEL,
                                 "poll_age_s": round(age, 2)})
                    self._detected[k] = (now, "wedge")
                    try:
                        proc.kill()
                        proc.wait(5.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                elif self._backoff.pending(k) \
                        and now - self._spawned_at.get(k, now) \
                        >= self._backoff.healthy_after:
                    self._backoff.note_healthy(k)
            # park pass: children beyond the desired fan-out stop
            # (graceful terminate -> batcher drain) and stay down; the
            # frontends' router fails their sockets over to survivors
            for k in self.engine_ids:
                if k in active:
                    continue
                proc = self._procs.get(k)
                if proc is None or proc.poll() is not None:
                    continue
                log.info("admission engine parked (scale-down)",
                         details={"engine": k})
                old = self._ctl.pop(k, None)
                if old is not None:
                    old.close()
                self._prev_stats.pop(k, None)
                self._knobs_pushed.pop(k, None)
                # a park mid-recovery cancels the recovery: down on
                # purpose now, not a fault being healed
                self._detected.pop(k, None)
                self._respawn_at.pop(k, None)
                from . import metrics as _metrics
                _metrics.zero_engine_gauges(self.engine_label(k))
                try:
                    proc.terminate()
                except OSError:
                    pass
            # two-pass respawn: spawn EVERY dead engine first, then
            # await readiness — concurrently-dead engines initialize
            # in parallel instead of head-of-line blocking on one
            # child's (potentially slow) JAX/device init
            spawned: list = []
            for k in self.engine_ids:
                if k not in active:
                    continue  # parked: dead on purpose, no respawn
                proc = self._procs.get(k)
                if proc is None or proc.poll() is None \
                        or self._stopping.is_set():
                    continue
                now = time.monotonic()
                if k not in self._detected:
                    log.warning("admission engine died; respawning",
                                details={"engine": k,
                                         "rc": proc.returncode,
                                         "supervisor":
                                             self.SUPERVISOR_LABEL})
                    self._detected[k] = (now, "death")
                if k not in self._respawn_at:
                    uptime = now - self._spawned_at.get(k, now)
                    self._respawn_at[k] = \
                        now + self._backoff.delay_for(k, uptime)
                if now < self._respawn_at[k]:
                    continue  # holding the crash-loop backoff delay
                old = self._ctl.pop(k, None)
                if old is not None:
                    old.close()
                self._prev_stats.pop(k, None)
                # the replacement process boots with configured
                # defaults: forget any knob ack so the newest
                # payload re-pushes after its resync
                self._knobs_pushed.pop(k, None)
                # the dead child's relayed engine-labeled gauges
                # must not export its last depth/duty while it is
                # down (respawn's first poll would eventually
                # overwrite them — or never, if respawn keeps
                # failing)
                from . import metrics as _metrics
                _metrics.zero_engine_gauges(self.engine_label(k))
                try:
                    spawned.append((k, self._spawn(k)))
                except Exception as e:
                    log.error("engine respawn failed",
                              details={"engine": k,
                                       "error": str(e)})
                    self._respawn_at[k] = time.monotonic() + \
                        self._backoff.delay_for(k, 0.0)
            for k, p in spawned:
                try:
                    self._await_ready(
                        k, p, time.monotonic() + self.ready_timeout)
                    self._procs[k] = p
                    self._ctl[k] = BackplaneClient(
                        self.socket_for(k), worker_id=f"ctl-{k}",
                        connect_timeout=5.0)
                    self._dirty[k] = True
                    self._respawn_at.pop(k, None)
                    self._backoff.respawned(k)
                    # sync NOW, not next pass: the engine refuses
                    # admission (NOT_READY) until this lands, so the
                    # shorter the window the less failover traffic
                    # the survivors absorb
                    self._resync(k)
                except Exception as e:
                    log.error("engine respawn failed",
                              details={"engine": k, "error": str(e)})
                    # the dead proc stays in _procs[k]: retried next
                    # pass; never leak the half-started child
                    try:
                        p.kill()
                    except OSError:
                        pass
                    self._respawn_at[k] = time.monotonic() + \
                        self._backoff.delay_for(k, 0.0)
            for k in self.engine_ids:
                if self._dirty.get(k) and k in self._ctl:
                    self._resync(k)
            # recovery accounting: a detected-failed child counts as
            # recovered once its replacement is alive AND resynced —
            # the wall clock from detection to here is the MTTR the
            # fault_recovery histogram exports
            for k in list(self._detected):
                proc = self._procs.get(k)
                if proc is None or proc.poll() is not None \
                        or self._dirty.get(k) or k not in self._ctl:
                    continue
                t0, fault = self._detected.pop(k)
                self._respawn_at.pop(k, None)
                from . import metrics as _metrics
                _metrics.report_fault_recovery(
                    self.RECOVERY_COMPONENT, fault,
                    time.monotonic() - t0)
            self._push_knobs()
            now = time.monotonic()
            if now - last_poll >= self.POLL_INTERVAL_S:
                last_poll = now
                self.poll_stats()
                self._report_fleet()

    def _report_fleet(self) -> None:
        from . import metrics

        # "configured" follows the DESIRED fan-out, not the ceiling:
        # a deliberately parked engine must read as converged
        # (desired == alive), while a dead unparked one reads as a
        # deficit the monitor is healing
        metrics.report_admission_engines(
            self.active_total(), 1 + self.alive_count())

    def poll_stats(self) -> None:
        """Pull each engine's relayed metric totals and merge the
        delta since the previous poll into this process's registry."""
        from . import metrics

        for k in self.engine_ids:
            ctl = self._ctl.get(k)
            if ctl is None:
                continue
            self._last_attempt[k] = time.monotonic()
            try:
                # the poll timeout bounds wedge-detection latency (a
                # SIGSTOP'd child is only detectable once its poll
                # EXPIRES), so scale it with the heartbeat deadline
                # instead of always waiting the full production 5 s
                cur = ctl.poll_stats(timeout=min(
                    5.0, max(1.0, self.heartbeat_deadline_s)))
            except BackplaneError:
                continue  # dead/respawning engine: next pass
            # an answered poll IS the heartbeat: only a child whose
            # read loop is actually scheduling can produce one
            self._last_ok[k] = time.monotonic()
            metrics.merge_engine_stats(cur, self._prev_stats.get(k))
            self._prev_stats[k] = cur

    def alive_count(self) -> int:
        return sum(1 for p in self._procs.values()
                   if p is not None and p.poll() is None)

    def monitoring(self) -> bool:
        """The supervisor's health signal: the monitor thread is still
        respawning dead engines (NOT all-alive — a dead child mid-
        respawn is a degraded-but-serving state)."""
        t = self._monitor
        return bool(t and t.is_alive()) and not self._stopping.is_set()

    def alive(self) -> bool:
        # measured against the DESIRED fan-out: parked children are
        # down on purpose and must not read as a fleet deficit
        return self.alive_count() == self._desired_children

    def kill_engine(self, k: int) -> None:
        """Chaos hook: SIGKILL one engine child (the monitor respawns
        it; frontends fail its in-flight requests over to survivors)."""
        proc = self._procs.get(k)
        if proc is not None and proc.poll() is None:
            proc.kill()

    def pause_engine(self, k: int) -> None:
        """Chaos hook: SIGSTOP one engine child — alive to waitpid,
        silent on the wire. Only the poll-age heartbeat deadline can
        catch this (the gray-failure case)."""
        proc = self._procs.get(k)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGSTOP)

    def resume_engine(self, k: int) -> None:
        proc = self._procs.get(k)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGCONT)

    def child_pids(self) -> dict[int, int]:
        """Live child pids by engine id (the chaos verifier's
        process-leak baseline)."""
        return {k: p.pid for k, p in self._procs.items()
                if p is not None and p.poll() is None}

    def stop(self, timeout: float = 15.0) -> None:
        self._stopping.set()
        for ctl in self._ctl.values():
            ctl.close()
        self._ctl.clear()
        for proc in self._procs.values():
            if proc is not None and proc.poll() is None:
                try:
                    # resume a SIGSTOP'd child first — see
                    # FrontendSupervisor.stop
                    os.kill(proc.pid, signal.SIGCONT)
                    proc.terminate()
                except OSError:
                    pass
        end = time.monotonic() + timeout
        for proc in self._procs.values():
            if proc is None:
                continue
            try:
                proc.wait(max(0.1, end - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(5.0)
                except subprocess.TimeoutExpired:
                    pass
        # stopped children's relayed engine-labeled gauges must not
        # outlive them on the primary's exposition
        from . import metrics
        for k in self.engine_ids:
            metrics.zero_engine_gauges(self.engine_label(k))
        self._backoff.close()


class AuditShardSupervisor(EngineSupervisor):
    """Spawns and supervises the N audit SHARD processes of the sharded
    inventory plane (`--serve auditslice`): same process-lifecycle,
    L-frame replication, and stats-merge machinery as the admission
    engines, plus

      * per-shard sync snapshots: the provider takes the shard id, so a
        respawned shard is refilled with ITS inventory slice (+ the
        join/namespace broadcast set), not the whole cluster;
      * a resync GENERATION per shard: bumped on every successful full
        sync, so the leader's sweep loop can tell "this shard was
        reborn since I last talked to it" and re-dispatch only the
        orphaned partition;
      * `sweep()`: the Q-frame request that runs one slice sweep on a
        shard's dedicated audit executor and returns its serialized
        per-kind results.

    Liveness rides the inherited M-frame poll-age heartbeat: slice
    sweeps run on the child's dedicated audit executor, so its read
    loop keeps answering polls through a multi-second sweep — only a
    genuinely wedged (SIGSTOP'd/hung) shard goes silent, gets killed,
    and is healed by respawn+resync; the leader's sweep retry then
    re-dispatches just the orphaned partition.
    """

    RECOVERY_COMPONENT = "audit_shard"
    SUPERVISOR_LABEL = "audit"

    def __init__(self, shard_count: int, socket_for, spawn_args=(),
                 snapshot_provider=None, ready_timeout: float = 180.0,
                 heartbeat_deadline_s: float = 10.0):
        super().__init__(range(shard_count), socket_for, spawn_args,
                         snapshot_provider=None,
                         ready_timeout=ready_timeout,
                         heartbeat_deadline_s=heartbeat_deadline_s)
        self.shard_count = int(shard_count)
        self._shard_snapshot = snapshot_provider  # (k) -> sync op
        self.generation: dict[int, int] = {k: 0 for k in self.engine_ids}

    def engine_label(self, k: int) -> str:
        return f"audit{k}"  # matches --engine-id in _spawn

    def _spawn(self, k: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "gatekeeper_tpu.control.engine",
               "--socket", self.socket_for(k),
               "--engine-id", f"audit{k}",
               "--device", str(k),
               "--serve", "auditslice",
               "--audit-shard-id", str(k),
               "--audit-shard-count",
               str(self.shard_count)] + self.spawn_args
        return subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)

    def _resync(self, k: int) -> None:
        provider = self._shard_snapshot
        if provider is None:
            self._dirty[k] = False
            return
        with self._lock:
            self._dirty[k] = False
            try:
                op = provider(k)
                op["op"] = "sync"
                self._ctl[k].control(op, timeout=300.0)
                self._last_ok[k] = time.monotonic()
                self.generation[k] = self.generation.get(k, 0) + 1
                log.info("audit shard resynced",
                         details={"shard": k,
                                  "generation": self.generation[k]})
            except Exception as e:
                self._dirty[k] = True
                log.warning("audit shard resync failed; will retry",
                            details={"shard": k, "error": str(e)})

    def send(self, k: int, op: dict, timeout: float = 30.0) -> None:
        """Targeted single-shard library/data op (an owned object's
        add/remove goes ONLY to its owner; replicate() stays for
        broadcast ops). Failures mark the shard dirty for a monitor
        resync — same contract as replicate()."""
        with self._lock:
            ctl = self._ctl.get(k)
            if ctl is None or self._dirty.get(k):
                return
            try:
                ctl.control(op, timeout=timeout)
            except BackplaneError as e:
                self._dirty[k] = True
                log.warning("audit shard op failed; shard marked for "
                            "resync",
                            details={"shard": k, "error": str(e)})

    def _report_fleet(self) -> None:
        from . import metrics

        metrics.report_audit_shard_fleet(self.shard_count,
                                         self.alive_count())

    def sweep(self, k: int, body: bytes,
              timeout_s: float = 600.0) -> tuple[int, bytes]:
        """Run one slice sweep on shard k. Raises BackplaneError when
        the shard is down/unreachable — the caller owns the respawn-
        and-retry round trip (the orphaned-partition re-sweep)."""
        ctl = self._ctl.get(k)
        if ctl is None:
            raise BackplaneError(f"audit shard {k} not connected")
        return ctl.call("/v1/auditslice", body, timeout_s=timeout_s,
                        deadline=time.monotonic() + timeout_s)


# ------------------------------------------------------- frontend process


def frontend_main(argv=None) -> int:
    """Entry point of one pre-forked frontend process
    (`python -m gatekeeper_tpu.control.backplane ...`): slim by design —
    no JAX, no client framework state, just HTTP + the backplane."""
    import argparse

    p = argparse.ArgumentParser(prog="gatekeeper-tpu-frontend")
    p.add_argument("--socket", required=True,
                   help="engine backplane socket(s); comma-separated "
                        "for the N-engine plane (the frontend routes "
                        "least-load with request-hash fallback)")
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--addr", default="")
    p.add_argument("--certfile", default="")
    p.add_argument("--keyfile", default="")
    p.add_argument("--worker-id", default="0")
    p.add_argument("--serve", default="admit,admitlabel,mutate")
    p.add_argument("--fail-closed", action="store_true")
    p.add_argument("--mutation-fail-closed", default="unset",
                   choices=["true", "false", "unset"],
                   help="mutation-webhook failure stance; 'unset' "
                        "inherits --fail-closed")
    p.add_argument("--default-timeout", type=float,
                   default=DEFAULT_WEBHOOK_TIMEOUT_S)
    p.add_argument("--trace-sample-rate", type=float, default=0.0,
                   help="fraction of requests traced at this edge "
                        "(stride-sampled; an inbound sampled "
                        "traceparent always traces)")
    p.add_argument("--shm-ring-mb", type=float, default=0.0,
                   help="shared-memory ring size (MB) for the zero-"
                        "copy backplane: review bytes ride a per-"
                        "frontend /dev/shm ring and the socket carries "
                        "descriptors only. 0 = inline payload frames")
    p.add_argument("--shm-ring-name", default="",
                   help="ring segment name prefix (the supervisor "
                        "passes a per-worker-slot name it can sweep "
                        "after a kill -9)")
    p.add_argument("--no-reuse-port", action="store_true")
    args = p.parse_args(argv)
    # the frontend is a sampling edge only — span context forwards to
    # the engine, which owns the recorder/metrics sinks
    gtrace.TRACER.configure(args.trace_sample_rate)
    sockets = [s for s in args.socket.split(",") if s]
    ring_prefix = args.shm_ring_name \
        or f"gk-bp-{os.getpid()}-{args.worker_id}"
    client = (BackplaneClient(sockets[0], worker_id=args.worker_id,
                              ring_mb=args.shm_ring_mb,
                              ring_prefix=ring_prefix)
              if len(sockets) == 1 else
              BackplaneRouter(sockets, worker_id=args.worker_id,
                              ring_mb=args.shm_ring_mb,
                              ring_prefix=ring_prefix))
    server = FrontendServer(
        client, port=args.port, addr=args.addr,
        certfile=args.certfile or None, keyfile=args.keyfile or None,
        reuse_port=not args.no_reuse_port,
        serve=tuple(s for s in args.serve.split(",") if s),
        fail_closed=args.fail_closed,
        mutation_fail_closed=(None if args.mutation_fail_closed == "unset"
                              else args.mutation_fail_closed == "true"),
        default_timeout=args.default_timeout,
        worker_id=args.worker_id)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    server.start()
    # long-lived-server GC tuning (mirrors the engine's Runtime.start):
    # everything built so far is permanent; freezing it out of the
    # collector's scan set keeps multi-hundred-ms gen-2 pauses out of
    # the admission tail (measured: max latency 1.2s -> ~25ms)
    import gc

    gc.collect()
    gc.freeze()
    # connect eagerly so the engine's connected-workers gauge reflects
    # the plane before the first request (reconnects are lazy per call)
    try:
        client.ensure_connected()
    except BackplaneError:
        pass  # engine not up yet; the first forward retries
    print(f"READY {server.port}", flush=True)

    def heartbeat():
        # 1/s liveness heartbeat on the supervisor pipe: the parent's
        # reader stamps each line, so a SIGSTOP'd/wedged frontend goes
        # silent and trips the heartbeat deadline. A closed pipe
        # (supervisor gone) ends the loop instead of crashing serving.
        while not stop.wait(1.0):
            try:
                print("HB", flush=True)
            except (OSError, ValueError):
                return

    threading.Thread(target=heartbeat, name="frontend-heartbeat",
                     daemon=True).start()
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(frontend_main())
