"""Closed-loop adaptive serving controller + degradation ladder.

Every serving knob PAPER.md's control plane exposes — micro-batch
`max_wait`/`max_batch`, the shed depth, engine fan-out, AOT pre-warm —
was a hand-tuned constant: PR 13 made saturation legible (seal
reasons, fill ratios, queue depths, duty cycle, burn rates) and PR
14's scrape proved the plane edge-bound, but a human still read the
scrape and picked the numbers. This module closes the loop.

`AdaptiveController` is one daemon thread that, each `interval`
seconds, samples the EXISTING signals — `SloEngine` burn rates,
batch seal-reason mix + mean fill ratio (registry counter/histogram
deltas), queue depth, per-engine duty cycle, backplane inflight —
and actuates a small set of DECLARED knobs:

  * `batch_max_wait` / `batch_max_batch` — from the seal-reason mix:
    a window dominated by max_wait seals at near-zero fill is a
    trickle paying the full collection wait for nothing (shrink the
    wait); a window dominated by full seals is engine-bound (grow the
    batch). A quiet or mixed window relaxes both back toward the
    configured baseline.
  * `shed_depth` — the availability burn rate crossing the SRE
    fast/slow alert bounds (14.4x over 5m / 6x over 1h) tightens the
    bounded queue so overload is answered at the edge instead of
    queueing into certain timeout; burn under 1.0 on both windows
    relaxes it back toward baseline.
  * `engine_fanout` — duty cycle vs inflight attribution: sustained
    high duty is engine-bound (unpark an engine, up to the configured
    fleet); idle duty with an idle edge parks one (scale-down), via
    `EngineSupervisor.scale_to` — non-blocking, the supervisor's
    monitor loop does the process work.
  * `prewarm` — library-generation churn triggers one off-thread AOT
    pre-warm pass so the first post-churn evaluation dispatches warm.

Every actuation flows through ONE gate (`_actuate`): clamped to the
knob's declared [lo, hi], rate-limited by a per-knob cooldown,
direction reversals additionally held back by a hysteresis window
(the anti-oscillation guarantee the bench gates on), recorded as an
`Actuation` (knob, old, new, direction, reason, bounds, clamped),
logged, and counted on
`gatekeeper_tpu_adaptive_actuations_total{knob,direction}`. The
`--adaptive-control` kill switch maps to `disarm()`: the loop stops
and every knob is restored to its captured baseline BIT-EXACTLY (the
baseline value object itself is re-applied, not a rounded replay).

The degradation ladder makes overload behavior an explicit ordered
policy instead of emergent:

  rung 0 `normal`        — no intervention.
  rung 1 `tighten_shed`  — shed_depth actuated down to its floor.
  rung 2 `cache_only`    — ValidationHandler serves decision-cache
                           hits and short-circuits only; misses shed
                           (429 + failure stance) without evaluation.
  rung 3 `fail_stance`   — every non-exempt admission answers per the
                           configured failure stance immediately.

Escalation requires the fast-burn alert bound to hold for
`ladder_dwell` consecutive ticks AFTER shed tightening bottomed out;
de-escalation requires both windows under burn 1.0 for
`ladder_clear` ticks — one rung per dwell, never a jump to the top.

gklint registers `AdaptiveController._loop` as a no-block entry: the
tick may take locks and wait on its pacing event but never sleeps,
never touches sockets/subprocess/kube, and spawns pre-warm on a
one-shot thread — so the control loop can never wedge the plane it
is steering.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from . import metrics
from .logging import logger
from .metrics import REGISTRY
from .slo import ALERT_REFERENCE

log = logger("adaptive")

# ladder rungs, in escalation order (indices are the gauge value and
# the ValidationHandler contract: >= 2 cache-only, >= 3 fail-stance)
RUNG_NORMAL = 0
RUNG_TIGHTEN_SHED = 1
RUNG_CACHE_ONLY = 2
RUNG_FAIL_STANCE = 3

_SIGNAL_METRICS = (
    "gatekeeper_tpu_batch_seal_total",
    "gatekeeper_tpu_batch_fill_ratio",
    "gatekeeper_tpu_queue_depth",
    "gatekeeper_tpu_device_duty_cycle",
    "gatekeeper_tpu_backplane_inflight",
    "admission_requests_shed_total",
)


class Actuation:
    """One knob movement, fully described: what moved, from/to, why,
    inside which declared bounds, and whether the target was clamped.
    The audit trail every self-tuning step leaves behind — /debug/
    adaptive dumps the recent ring, the log line carries the same
    fields, and the {knob,direction} counter aggregates them."""

    __slots__ = ("knob", "old", "new", "direction", "reason",
                 "lo", "hi", "clamped", "t")

    def __init__(self, knob: str, old, new, direction: str,
                 reason: str, lo, hi, clamped: bool, t: float):
        self.knob = knob
        self.old = old
        self.new = new
        self.direction = direction
        self.reason = reason
        self.lo = lo
        self.hi = hi
        self.clamped = clamped
        self.t = t

    def describe(self) -> dict:
        return {"knob": self.knob, "old": self.old, "new": self.new,
                "direction": self.direction, "reason": self.reason,
                "bounds": [self.lo, self.hi], "clamped": self.clamped}


class Knob:
    """One declared actuator: getter/setter plus the bounds and rate
    limits every movement is clamped under. `baseline` is captured at
    arm() time — the configured value disarm() restores bit-exactly."""

    def __init__(self, name: str, get: Callable[[], float],
                 set_: Callable[[float], None], lo, hi,
                 cooldown_s: float = 5.0, integer: bool = False):
        self.name = name
        self.get = get
        self.set = set_
        self.lo = lo
        self.hi = hi
        self.cooldown_s = cooldown_s
        self.integer = integer
        self.baseline = None      # captured at arm()
        self.last_dir: Optional[str] = None
        self.last_t: Optional[float] = None
        self.flips = 0            # landed direction reversals
        self.suppressed = 0       # actuations held by cooldown/hysteresis

    def describe(self) -> dict:
        return {"value": self.get(), "baseline": self.baseline,
                "bounds": [self.lo, self.hi],
                "cooldown_s": self.cooldown_s,
                "last_direction": self.last_dir, "flips": self.flips,
                "suppressed": self.suppressed}


class DegradationLadder:
    """Thread-safe current rung + transition history. Consumers
    (ValidationHandler) only read `.rung`; only the controller (or a
    test) moves it. Reports the rung gauge and the per-rung
    transition counter on every move."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rung = RUNG_NORMAL
        self.transitions = 0
        self.history: deque = deque(maxlen=64)
        metrics.report_degradation_rung(self._rung)

    @property
    def rung(self) -> int:
        return self._rung

    @property
    def name(self) -> str:
        return metrics.DEGRADATION_RUNGS[
            min(self._rung, len(metrics.DEGRADATION_RUNGS) - 1)]

    def set(self, rung: int, reason: str = "") -> bool:
        rung = min(RUNG_FAIL_STANCE, max(RUNG_NORMAL, int(rung)))
        with self._lock:
            if rung == self._rung:
                return False
            old = self._rung
            self._rung = rung
            self.transitions += 1
            self.history.append(
                {"from": old, "to": rung, "reason": reason,
                 "t": time.time()})
        metrics.report_degradation_rung(rung)
        log.info("degradation rung %d -> %d" % (old, rung),
                 details={"reason": reason,
                          "rung": metrics.DEGRADATION_RUNGS[rung]})
        return True

    def describe(self) -> dict:
        return {"rung": self._rung, "name": self.name,
                "transitions": self.transitions,
                "history": list(self.history)}


class AdaptiveController:
    """The closed loop. Construct with whatever actuators this process
    owns (each optional — an audit-only pod gets a controller that
    only watches), `arm()` to capture baselines and start the tick
    thread, `disarm()` to stop and restore every baseline."""

    def __init__(self, batcher=None, engines=None, slo=None,
                 generation: Optional[Callable[[], int]] = None,
                 prewarm: Optional[Callable[[], int]] = None,
                 on_actuate: Optional[Callable] = None,
                 registry=REGISTRY,
                 interval: float = 1.0,
                 hysteresis_s: float = 10.0,
                 cooldown_s: float = 5.0,
                 fanout_cooldown_s: float = 30.0,
                 prewarm_cooldown_s: float = 30.0,
                 fill_low: float = 0.25,
                 seal_dominance: float = 0.8,
                 min_seals: int = 3,
                 duty_high: float = 0.75,
                 duty_low: float = 0.10,
                 relax_after_s: float = 30.0,
                 ladder_dwell: int = 5,
                 ladder_clear: int = 10,
                 max_wait_lo: float = 0.0005,
                 max_wait_hi: float = 0.05,
                 max_batch_lo: int = 16,
                 max_batch_hi: int = 4096,
                 shed_floor_frac: float = 0.125):
        self.registry = registry
        self.slo = slo
        self.batcher = batcher
        self.engines = engines
        self.generation = generation
        self.prewarm = prewarm
        # post-actuation hook (Actuation -> None): Runtime replicates
        # batcher-knob movements to engine children through it. Must
        # itself be non-blocking — it runs on the control loop.
        self.on_actuate = on_actuate
        self.interval = max(0.05, interval)
        self.hysteresis_s = hysteresis_s
        self.fill_low = fill_low
        self.seal_dominance = seal_dominance
        self.min_seals = min_seals
        self.duty_high = duty_high
        self.duty_low = duty_low
        self.relax_after_s = relax_after_s
        self.ladder_dwell = max(1, ladder_dwell)
        self.ladder_clear = max(1, ladder_clear)
        self.prewarm_cooldown_s = prewarm_cooldown_s
        self.ladder = DegradationLadder()
        self.knobs: dict[str, Knob] = {}
        if batcher is not None:
            self.knobs["batch_max_wait"] = Knob(
                "batch_max_wait",
                lambda: batcher.max_wait,
                lambda v: batcher.set_knobs(max_wait=v),
                max_wait_lo, max_wait_hi, cooldown_s=cooldown_s)
            self.knobs["batch_max_batch"] = Knob(
                "batch_max_batch",
                lambda: batcher.max_batch,
                lambda v: batcher.set_knobs(max_batch=v),
                max_batch_lo, max_batch_hi, cooldown_s=cooldown_s,
                integer=True)
            # shed floor derives from the configured depth at arm();
            # 0 (unbounded) stays unbounded — there is no meaningful
            # tightening of "no bound" (the ladder still covers it)
            self._shed_floor_frac = shed_floor_frac
            self.knobs["shed_depth"] = Knob(
                "shed_depth",
                lambda: batcher.max_queue,
                lambda v: batcher.set_knobs(max_queue=v),
                1, 1 << 20, cooldown_s=cooldown_s, integer=True)
        if engines is not None:
            self.knobs["engine_fanout"] = Knob(
                "engine_fanout",
                engines.active_total,
                engines.scale_to,
                1, 1 + len(engines.engine_ids),
                cooldown_s=fanout_cooldown_s, integer=True)
        self._history: deque = deque(maxlen=256)
        self._prev_snap: Optional[dict] = None
        self._last_gen: Optional[int] = None
        self._gen_settled = False
        self._last_prewarm_t: Optional[float] = None
        self._last_busy_t = time.monotonic()
        self._burn_hot_ticks = 0
        self._burn_clear_ticks = 0
        self._armed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.ticks = 0
        self._last_signals: dict = {}

    # ------------------------------------------------------- lifecycle

    def arm(self) -> None:
        """Capture every knob's configured value as its baseline and
        start the control loop. Idempotent."""
        with self._lock:
            if self._armed:
                return
            for knob in self.knobs.values():
                knob.baseline = knob.get()
                metrics.report_adaptive_knob(knob.name, knob.baseline)
            shed = self.knobs.get("shed_depth")
            if shed is not None:
                if shed.baseline:
                    shed.lo = max(1, int(shed.baseline
                                         * self._shed_floor_frac))
                    shed.hi = int(shed.baseline)
                else:
                    # unbounded queue: leave the knob parked
                    shed.lo = shed.hi = 0
            self._armed = True
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="adaptive",
                                            daemon=True)
            self._thread.start()
        log.info("adaptive controller armed",
                 details={"knobs": sorted(self.knobs),
                          "interval_s": self.interval})

    def disarm(self, restore: bool = True) -> None:
        """Kill switch: stop the loop and (by default) restore every
        knob to its captured baseline bit-exactly. Idempotent."""
        with self._lock:
            if not self._armed:
                return
            self._armed = False
            self._stop.set()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=10.0)
        if restore:
            now = time.monotonic()
            for knob in self.knobs.values():
                if knob.baseline is None:
                    continue
                old = knob.get()
                if old == knob.baseline:
                    continue
                knob.set(knob.baseline)
                act = Actuation(knob.name, old, knob.baseline,
                                "restore", "disarm: baseline restore",
                                knob.lo, knob.hi, False, now)
                self._history.append(act)
                metrics.report_adaptive_actuation(knob.name, "restore")
                metrics.report_adaptive_knob(knob.name, knob.baseline)
                log.info("knob restored to baseline",
                         details=act.describe())
                self._notify(act)
            self.ladder.set(RUNG_NORMAL, "disarm")
        log.info("adaptive controller disarmed",
                 details={"restored": restore})

    @property
    def armed(self) -> bool:
        return self._armed

    def healthy(self) -> bool:
        t = self._thread
        return not self._armed or bool(t and t.is_alive())

    # ------------------------------------------------------ the loop

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # the controller must never crash
                log.warning("adaptive tick failed", details=str(e))

    def tick(self, now: Optional[float] = None) -> dict:
        """One control iteration: sample -> decide -> actuate. Public
        so tests (and the bench harness) can drive the loop
        deterministically without the thread."""
        now = now if now is not None else time.monotonic()
        signals = self._sample(now)
        self._last_signals = signals
        self.ticks += 1
        self._steer_batch_shape(signals, now)
        self._steer_ladder(signals, now)
        self._steer_fanout(signals, now)
        self._steer_prewarm(now)
        return signals

    # ------------------------------------------------------- sampling

    def _sample(self, now: float) -> dict:
        snap = self.registry.snapshot(_SIGNAL_METRICS)
        prev, self._prev_snap = self._prev_snap, snap
        seals = self._counter_deltas(
            snap, prev, "gatekeeper_tpu_batch_seal_total",
            match={"plane": "admission"}, by="reason")
        fill = self._hist_mean_delta(
            snap, prev, "gatekeeper_tpu_batch_fill_ratio",
            match={"plane": "admission"})
        shed = sum(self._counter_deltas(
            snap, prev, "admission_requests_shed_total").values())
        depth = sum(v for _, v in self._gauge_values(
            snap, "gatekeeper_tpu_queue_depth",
            match={"queue": "admission"}))
        duties = [v for _, v in self._gauge_values(
            snap, "gatekeeper_tpu_device_duty_cycle")]
        inflight = sum(v for _, v in self._gauge_values(
            snap, "gatekeeper_tpu_backplane_inflight"))
        burn = {}
        if self.slo is not None:
            burn = (self.slo.latest() or {}).get("availability") or {}
        return {
            "seals": seals,
            "seal_total": sum(seals.values()),
            "mean_fill": fill,
            "shed_delta": shed,
            "queue_depth": depth,
            "duty_max": max(duties) if duties else 0.0,
            "inflight": inflight,
            "burn_5m": (burn.get("5m") or {}).get("burn_rate", 0.0),
            "burn_1h": (burn.get("1h") or {}).get("burn_rate", 0.0),
        }

    @staticmethod
    def _entries(snap: Optional[dict], name: str):
        ent = (snap or {}).get(name)
        if not ent:
            return (), ()
        return tuple(ent.get("labels") or ()), ent

    def _gauge_values(self, snap, name, match=None):
        labels, ent = self._entries(snap, name)
        out = []
        for key, v in (ent.get("values") or []) if ent else []:
            lab = dict(zip(labels, tuple(key)))
            if match and any(lab.get(mk) != mv
                             for mk, mv in match.items()):
                continue
            out.append((lab, v))
        return out

    def _counter_deltas(self, snap, prev, name, match=None, by=None):
        cur = {tuple(k): v for k, v in self._raw_values(snap, name)}
        old = {tuple(k): v for k, v in self._raw_values(prev, name)}
        labels, _ = self._entries(snap, name)
        out: dict = {}
        for key, v in cur.items():
            lab = dict(zip(labels, key))
            if match and any(lab.get(mk) != mv
                             for mk, mv in match.items()):
                continue
            d = v - old.get(key, 0.0)
            if d <= 0:
                continue
            bucket = lab.get(by, "") if by else ""
            out[bucket] = out.get(bucket, 0.0) + d
        return out

    @staticmethod
    def _raw_values(snap, name):
        ent = (snap or {}).get(name) or {}
        return ent.get("values") or []

    def _hist_mean_delta(self, snap, prev, name, match=None):
        labels, ent = self._entries(snap, name)
        if not ent:
            return None
        old = {tuple(k): (s, n)
               for k, _, s, n in
               (((prev or {}).get(name) or {}).get("hist") or [])}
        dsum = dcount = 0.0
        for k, _, s, n in ent.get("hist") or []:
            lab = dict(zip(labels, tuple(k)))
            if match and any(lab.get(mk) != mv
                             for mk, mv in match.items()):
                continue
            ps, pn = old.get(tuple(k), (0.0, 0))
            dsum += s - ps
            dcount += n - pn
        if dcount <= 0:
            return None
        return dsum / dcount

    # ------------------------------------------------------ policies

    def _steer_batch_shape(self, signals: dict, now: float) -> None:
        wait = self.knobs.get("batch_max_wait")
        batch = self.knobs.get("batch_max_batch")
        if wait is None or batch is None:
            return
        total = signals["seal_total"]
        seals = signals["seals"]
        fill = signals["mean_fill"]
        if total >= self.min_seals:
            self._last_busy_t = now
            if (seals.get("max_wait", 0.0) / total
                    >= self.seal_dominance
                    and fill is not None and fill <= self.fill_low):
                # edge trickle: every batch waits the full window to
                # seal near-empty — the wait is pure added latency
                self._actuate(wait, wait.get() * 0.5,
                              "max_wait-sealed at fill %.2f" % fill,
                              now)
                return
            if seals.get("full", 0.0) / total >= self.seal_dominance:
                # engine-bound: batches seal full — amortize further
                self._actuate(batch, batch.get() * 2,
                              "full-sealed: growing batch", now)
                return
        if now - self._last_busy_t >= self.relax_after_s:
            # quiet plane: drift both knobs back toward the
            # configured baseline one cooldown-paced step at a time
            for knob in (wait, batch):
                if knob.baseline is None or knob.get() == knob.baseline:
                    continue
                cur = knob.get()
                target = (min(cur * 2, knob.baseline) if
                          cur < knob.baseline
                          else max(cur / 2, knob.baseline))
                self._actuate(knob, target, "relax toward baseline",
                              now)

    def _steer_ladder(self, signals: dict, now: float) -> None:
        shed = self.knobs.get("shed_depth")
        fast_ref = ALERT_REFERENCE.get("5m", 14.4)
        slow_ref = ALERT_REFERENCE.get("1h", 6.0)
        hot = (signals["burn_5m"] >= fast_ref
               or signals["burn_1h"] >= slow_ref)
        clear = signals["burn_5m"] < 1.0 and signals["burn_1h"] < 1.0
        if hot:
            self._burn_hot_ticks += 1
            self._burn_clear_ticks = 0
        elif clear:
            self._burn_clear_ticks += 1
            self._burn_hot_ticks = 0
        else:
            self._burn_hot_ticks = 0
            self._burn_clear_ticks = 0
        tightened_out = True
        if shed is not None and shed.hi:
            if hot:
                self.ladder.set(max(self.ladder.rung,
                                    RUNG_TIGHTEN_SHED),
                                "availability burn %.1fx/%.1fx over "
                                "alert bounds"
                                % (signals["burn_5m"],
                                   signals["burn_1h"]))
                self._actuate(shed, shed.get() // 2,
                              "availability burn over alert bounds",
                              now)
            elif clear and self.ladder.rung <= RUNG_TIGHTEN_SHED \
                    and shed.get() < shed.hi:
                self._actuate(shed, min(shed.get() * 2, shed.hi),
                              "burn clear: relaxing shed depth", now)
            tightened_out = shed.get() <= shed.lo
        if hot and tightened_out \
                and self._burn_hot_ticks >= self.ladder_dwell:
            # tightening alone is not holding the budget: climb ONE
            # rung, then require a fresh dwell before the next
            if self.ladder.set(self.ladder.rung + 1,
                               "burn held %dx dwell after shed floor"
                               % self._burn_hot_ticks):
                self._burn_hot_ticks = 0
        if self._burn_clear_ticks >= self.ladder_clear \
                and self.ladder.rung > RUNG_NORMAL:
            rung = self.ladder.rung - 1
            if rung == RUNG_TIGHTEN_SHED and shed is not None \
                    and shed.hi and shed.get() >= shed.hi:
                rung = RUNG_NORMAL  # shed already relaxed: skip rung 1
            if self.ladder.set(rung, "burn clear %d ticks"
                               % self._burn_clear_ticks):
                self._burn_clear_ticks = 0

    def _steer_fanout(self, signals: dict, now: float) -> None:
        fan = self.knobs.get("engine_fanout")
        if fan is None:
            return
        cur = fan.get()
        if signals["duty_max"] >= self.duty_high and cur < fan.hi:
            # engine-bound: evaluators busy — add capacity
            self._actuate(fan, cur + 1,
                          "duty %.2f: engine-bound" % signals["duty_max"],
                          now)
        elif (signals["duty_max"] <= self.duty_low
              and signals["inflight"] <= 1.0
              and signals["queue_depth"] <= 1.0
              and cur > fan.lo):
            # edge- or nothing-bound: park an engine (the supervisor
            # keeps the process warm to respawn on the next step-up)
            self._actuate(fan, cur - 1,
                          "duty %.2f, idle edge: parking engine"
                          % signals["duty_max"], now)

    def _steer_prewarm(self, now: float) -> None:
        if self.generation is None or self.prewarm is None:
            return
        try:
            gen = self.generation()
        except Exception:
            return
        if self._last_gen is None:
            self._last_gen = gen
            return
        if gen != self._last_gen:
            # churn in flight: wait for a settled tick so one burst of
            # template ingestion triggers ONE pre-warm, not one per op
            self._last_gen = gen
            self._gen_settled = False
            return
        if self._gen_settled:
            return
        self._gen_settled = True
        if self._last_prewarm_t is not None and \
                now - self._last_prewarm_t < self.prewarm_cooldown_s:
            return
        self._last_prewarm_t = now
        prewarm = self.prewarm

        def run():
            try:
                n = prewarm()
                log.info("adaptive pre-warm pass finished",
                         details={"programs": n})
            except Exception as e:
                log.warning("adaptive pre-warm failed", details=str(e))

        threading.Thread(target=run, name="adaptive-prewarm",
                         daemon=True).start()
        act = Actuation("prewarm", 0, 1, "up",
                        "library generation settled at %d"
                        % self._last_gen, 0, 1, False, now)
        self._history.append(act)
        metrics.report_adaptive_actuation("prewarm", "up")
        log.info("adaptive pre-warm spawned", details=act.describe())

    # ------------------------------------------------------ actuation

    def _actuate(self, knob: Knob, target, reason: str,
                 now: float) -> Optional[Actuation]:
        """The single gate every knob movement passes: clamp, rate
        limit (cooldown + reversal hysteresis), apply, record."""
        lo, hi = knob.lo, knob.hi
        new = min(hi, max(lo, target))
        clamped = new != target
        if knob.integer:
            new = int(round(new))
        old = knob.get()
        if new == old:
            return None
        direction = "up" if new > old else "down"
        if knob.last_t is not None:
            since = now - knob.last_t
            if direction == knob.last_dir and since < knob.cooldown_s:
                knob.suppressed += 1
                return None
            if direction != knob.last_dir and since < self.hysteresis_s:
                # a reversal this soon IS oscillation: hold the knob
                knob.suppressed += 1
                return None
        knob.set(new)
        if knob.last_dir is not None and direction != knob.last_dir:
            knob.flips += 1
        knob.last_dir = direction
        knob.last_t = now
        act = Actuation(knob.name, old, new, direction, reason,
                        lo, hi, clamped, now)
        self._history.append(act)
        metrics.report_adaptive_actuation(knob.name, direction)
        metrics.report_adaptive_knob(knob.name, new)
        log.info("adaptive actuation", details=act.describe())
        self._notify(act)
        return act

    def _notify(self, act: Actuation) -> None:
        if self.on_actuate is None:
            return
        try:
            self.on_actuate(act)
        except Exception as e:
            log.warning("actuation hook failed", details=str(e))

    # ---------------------------------------------------------- views

    def flip_count(self) -> int:
        """Total landed direction reversals across all knobs — the
        oscillation measure the bench gate reads."""
        return sum(k.flips for k in self.knobs.values())

    def actuations(self) -> list:
        return [a.describe() for a in self._history]

    def status(self, query: str = "") -> dict:
        """/debug/adaptive payload."""
        return {
            "armed": self._armed,
            "interval_s": self.interval,
            "hysteresis_s": self.hysteresis_s,
            "ticks": self.ticks,
            "ladder": self.ladder.describe(),
            "knobs": {name: k.describe()
                      for name, k in sorted(self.knobs.items())},
            "flip_count": self.flip_count(),
            "signals": self._last_signals,
            "actuations": self.actuations()[-32:],
        }
