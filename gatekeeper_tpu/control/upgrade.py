"""Storage-version upgrade manager.

Counterpart of the reference pkg/upgrade/manager.go:80-158: a one-shot
pass at startup that touches every v1alpha1 constraint and template (a
no-op update) so the apiserver rewrites them at the current storage
version (v1beta1).
"""

from __future__ import annotations

from .kube import KubeError
from .logging import logger

log = logger("upgrade")

TEMPLATE_GVK = ("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"


class UpgradeManager:
    def __init__(self, kube):
        self.kube = kube

    def upgrade(self) -> int:
        """Touch templates + all constraint kinds; returns objects touched."""
        touched = 0
        kinds = [TEMPLATE_GVK]
        try:
            for res in self.kube.server_preferred_resources():
                if res.get("group") == CONSTRAINT_GROUP:
                    kinds.append((res["group"], res["version"], res["kind"]))
        except KubeError:
            pass
        for gvk in kinds:
            try:
                objs = self.kube.list(gvk)
            except KubeError:
                continue
            for obj in objs:
                try:
                    self.kube.update(obj)
                    touched += 1
                except KubeError:
                    continue
        if touched:
            log.info("storage-version upgrade complete",
                     details={"objects": touched})
        return touched
