"""Kubernetes API access: protocol, in-memory fake, REST client.

The control plane (controllers/audit/webhook/certs) talks to this seam
only. `FakeKube` is the test double standing in for envtest (SURVEY.md §4
tier 3: the reference boots etcd+apiserver; here an in-memory apiserver
model with watch streams gives the same reconciler-level coverage without
binaries). `RestKubeClient` is the production path (kubeconfig/in-cluster
service account against the real API server).

Objects are unstructured dicts. GVKs are (group, version, kind) tuples;
resources are addressed by (gvk, namespace, name).
"""

from __future__ import annotations

import copy
import json
import os
import random
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Callable, Optional

GVK = tuple  # (group, version, kind)


class KubeError(Exception):
    """code carries the HTTP status when one applies (str(e) stays the
    plain message — log call sites render details=str(e))."""

    def __init__(self, message: str = "", code: Optional[int] = None):
        super().__init__(message)
        self.code = code

    def __str__(self) -> str:
        return self.args[0] if self.args else ""


class Conflict(KubeError):
    pass


class NotFound(KubeError):
    pass


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: dict


def gvk_of(obj: dict) -> GVK:
    api_version = obj.get("apiVersion") or ""
    group, _, version = api_version.rpartition("/")
    return (group, version, obj.get("kind") or "")


def _key(obj: dict) -> tuple:
    meta = obj.get("metadata") or {}
    return (meta.get("namespace") or "", meta.get("name") or "")


class FakeKube:
    """In-memory cluster: CRUD + watch streams + discovery.

    Thread-safe; watch subscribers get events through callback queues the
    watch manager drains. Maintains resourceVersion counters and performs
    conflict detection on update, mirroring apiserver semantics the
    reconcilers rely on (retry loops, status subresource).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._store: dict[GVK, dict[tuple, dict]] = {}
        self._rv = 0
        self._compacted_rv = 0  # resume floor: older RVs must relist
        # deletion tombstones (rv, gvk, obj): a watch resuming from a
        # valid RV must replay deletes that happened while the client
        # was down, exactly as a real apiserver's event history does.
        # Bounded; trimming advances the compaction floor so resumes
        # older than the retained history take the relist path.
        self._deleted: list[tuple] = []
        self._watchers: dict[GVK, list[Callable[[WatchEvent], None]]] = {}
        # discovery: gvk -> {"namespaced": bool, "verbs": [...]}
        self._discovery: dict[GVK, dict] = {}
        # mutation/list call log, for tests asserting API write counts
        # (e.g. the audit's delta'd status PATCHes). Bounded: --fake-kube
        # also backs long-running dev control planes, which must not
        # accumulate one tuple per API call forever
        self.calls: list[tuple] = []

    _CALL_LOG_CAP = 100_000

    # watch(resource_version=...) settles SYNCHRONOUSLY: by the time it
    # returns, the replay was delivered and on_gap (if due) has fired.
    # The tracker's warm-restart validation trusts such resumes without
    # a list-diff; asynchronous clients (the REST streamer, whose 410
    # arrives a round-trip later) must leave this False so restored
    # state is re-validated against a live list instead.
    watch_resume_synchronous = True

    def _record(self, call: tuple) -> None:
        if len(self.calls) >= self._CALL_LOG_CAP:
            del self.calls[: self._CALL_LOG_CAP // 2]
        self.calls.append(call)

    # ------------------------------------------------------------ discovery

    def register_kind(self, gvk: GVK, namespaced: bool = True,
                      listable: bool = True) -> None:
        with self._lock:
            verbs = ["get", "create", "update", "delete", "watch"]
            if listable:
                verbs.append("list")
            self._discovery[gvk] = {"namespaced": namespaced, "verbs": verbs}

    def server_preferred_resources(self) -> list[dict]:
        """Discovery listing (reference audit manager.go:195-229)."""
        with self._lock:
            out = []
            for (g, v, k), info in self._discovery.items():
                out.append({"group": g, "version": v, "kind": k,
                            "namespaced": info["namespaced"],
                            "verbs": list(info["verbs"])})
            return out

    # ---------------------------------------------------------------- CRUD

    def _bump(self) -> str:
        self._rv += 1
        return str(self._rv)

    def create(self, obj: dict) -> dict:
        with self._lock:
            gvk = gvk_of(obj)
            self._record(("create", gvk, _key(obj)))
            bucket = self._store.setdefault(gvk, {})
            key = _key(obj)
            if key in bucket:
                raise Conflict(f"{gvk} {key} already exists")
            stored = copy.deepcopy(obj)
            stored.setdefault("metadata", {})["resourceVersion"] = self._bump()
            bucket[key] = stored
        # notify OUTSIDE the lock: subscribers (watch manager fan-out) take
        # their own locks and may call back into this client — holding our
        # lock here is a lock-order inversion with WatchManager._lock
        self._notify(gvk, WatchEvent("ADDED", copy.deepcopy(stored)))
        return copy.deepcopy(stored)

    def get(self, gvk: GVK, name: str, namespace: str = "") -> dict:
        with self._lock:
            obj = self._store.get(tuple(gvk), {}).get((namespace, name))
            if obj is None:
                raise NotFound(f"{gvk} {namespace}/{name}")
            return copy.deepcopy(obj)

    def update(self, obj: dict, subresource: str = "") -> dict:
        with self._lock:
            gvk = gvk_of(obj)
            self._record(("update", gvk, _key(obj), subresource))
            bucket = self._store.setdefault(gvk, {})
            key = _key(obj)
            cur = bucket.get(key)
            if cur is None:
                raise NotFound(f"{gvk} {key}")
            rv = (obj.get("metadata") or {}).get("resourceVersion")
            cur_rv = (cur.get("metadata") or {}).get("resourceVersion")
            if rv is not None and rv != cur_rv:
                raise Conflict(f"{gvk} {key}: resourceVersion {rv} != {cur_rv}")
            stored = copy.deepcopy(obj)
            if subresource == "status":
                # status updates only touch .status
                merged = copy.deepcopy(cur)
                merged["status"] = copy.deepcopy(obj.get("status"))
                stored = merged
            stored.setdefault("metadata", {})["resourceVersion"] = self._bump()
            bucket[key] = stored
        self._notify(gvk, WatchEvent("MODIFIED", copy.deepcopy(stored)))
        return copy.deepcopy(stored)

    def apply(self, obj: dict) -> dict:
        """create-or-update convenience."""
        try:
            return self.create(obj)
        except Conflict:
            meta = obj.setdefault("metadata", {})
            cur = self.get(gvk_of(obj), meta.get("name") or "",
                           meta.get("namespace") or "")
            meta["resourceVersion"] = cur["metadata"]["resourceVersion"]
            return self.update(obj)

    _DELETE_LOG_CAP = 10_000

    def delete(self, gvk: GVK, name: str, namespace: str = "") -> None:
        with self._lock:
            self._record(("delete", tuple(gvk), (namespace, name)))
            bucket = self._store.get(tuple(gvk), {})
            obj = bucket.pop((namespace, name), None)
            if obj is None:
                raise NotFound(f"{gvk} {namespace}/{name}")
            # tombstone at its own RV (apiserver semantics): resumed
            # watches replay it; trimming moves the compaction floor so
            # resumes predating retained history must relist instead
            self._deleted.append((int(self._bump()), tuple(gvk),
                                  copy.deepcopy(obj)))
            if len(self._deleted) > self._DELETE_LOG_CAP:
                cut = len(self._deleted) // 2
                self._compacted_rv = max(self._compacted_rv,
                                         self._deleted[cut - 1][0])
                del self._deleted[:cut]
        self._notify(tuple(gvk), WatchEvent("DELETED", copy.deepcopy(obj)))

    def list(self, gvk: GVK, namespace: Optional[str] = None) -> list[dict]:
        # apiserver-flap chaos: kube.list simulates the control plane's
        # read path degrading — 410 storms (compaction raced the list),
        # 429 rate limiting, 5xx blips, or a stalled response (sleep).
        # Armed with a rate (kube.list:error:429@0.5) it flaps rather
        # than hard-fails, which is the gray shape real apiservers show.
        from ..utils import faults
        flt = faults.consume("kube.list", gvk=tuple(gvk))
        if flt is not None:
            mode, param = flt
            if mode == "sleep":
                time.sleep(float(param) if param else 1.0)
            else:
                try:
                    code = int(param) if param else 503
                except ValueError:
                    code = 503
                raise KubeError(
                    f"injected apiserver fault on list ({code})",
                    code=code)
        with self._lock:
            self._record(("list", tuple(gvk), namespace))
            out = []
            for (ns, _), obj in sorted(self._store.get(tuple(gvk), {}).items()):
                if namespace is None or ns == namespace:
                    out.append(copy.deepcopy(obj))
            return out

    # --------------------------------------------------------------- watch

    def compact(self) -> None:
        """Chaos/test helper mirroring etcd compaction: resuming a watch
        from any RV issued before this call behaves like a 410 Gone —
        the full relist-style replay instead of a delta resume."""
        with self._lock:
            self._compacted_rv = self._rv

    def watch(self, gvk: GVK, callback: Callable[[WatchEvent], None],
              send_initial: bool = True, resource_version: str = "",
              on_gap: Optional[Callable[[], None]] = None) -> Callable[[], None]:
        """Subscribe; returns an unsubscribe fn. With send_initial, current
        objects are delivered as ADDED first (informer list+watch).

        With resource_version, delivery RESUMES from that point: the
        deletion tombstones and current objects newer than the RV replay
        (DELETED first, then MODIFIED — so a delete-then-recreate lands
        in the right final state) and nothing else — a restart that
        persisted its RV sees no duplicate ADDED storm and misses no
        delete. An RV older than the compaction floor (compact(), or
        tombstone trimming) takes the 410-gap path instead: on_gap fires
        (the subscriber schedules its list-diff reconcile) and every
        live object replays as ADDED for the state map to dedupe."""
        resume: Optional[int] = None
        deletes: list[dict] = []
        changed: list[dict] = []
        if resource_version:
            try:
                resume = int(resource_version)
            except ValueError:
                resume = None
        gap = False
        with self._lock:
            if resume is not None and resume < self._compacted_rv:
                resume = None  # too old: full relist-style replay
                send_initial = True
                gap = True
            elif resume is not None:
                # replay snapshot AND registration under ONE lock hold:
                # store commits happen under this lock, so no event can
                # land between the snapshot and the subscription (a
                # commit before the snapshot is in the replay AND may
                # notify us too — duplicates are (uid, rv) no-ops for
                # the subscriber's state map). Only objects NEWER than
                # the resume point are copied out — the warm-boot fast
                # path must not deep-copy the whole unchanged bucket.
                deletes = [copy.deepcopy(d[2]) for d in self._deleted
                           if d[0] > resume and d[1] == tuple(gvk)]
                for obj in self._store.get(tuple(gvk), {}).values():
                    try:
                        orv = int((obj.get("metadata") or {})
                                  .get("resourceVersion") or 0)
                    except ValueError:
                        orv = resume + 1  # deliver; state map decides
                    if orv > resume:
                        changed.append(copy.deepcopy(obj))
            if resume is not None:
                self._watchers.setdefault(tuple(gvk), []).append(callback)
        if gap and on_gap is not None:
            on_gap()
        if resume is None:
            initial = self.list(gvk) if send_initial else []
            with self._lock:
                self._watchers.setdefault(tuple(gvk),
                                          []).append(callback)
            for obj in initial:
                callback(WatchEvent("ADDED", obj))
        else:
            for obj in deletes:
                callback(WatchEvent("DELETED", obj))
            for obj in changed:
                callback(WatchEvent("MODIFIED", obj))

        def cancel():
            with self._lock:
                subs = self._watchers.get(tuple(gvk), [])
                if callback in subs:
                    subs.remove(callback)

        return cancel

    def _notify(self, gvk: GVK, event: WatchEvent) -> None:
        with self._lock:  # snapshot only; callbacks run outside the lock
            subs = list(self._watchers.get(tuple(gvk), []))
        for cb in subs:
            cb(event)


class ScopedKube:
    """Read view of a kube client restricted to ONE audit shard's slice.

    An ownership predicate over (gvk, namespace) — the consistent-hash
    partition key of the sharded audit plane — filters what `list`
    returns and which watch events reach the subscriber, so the
    InventoryTracker behind this wrapper maintains watches, resume RVs,
    and a (uid, rv) state map for exactly its slice and nothing else.
    Everything the predicate does not govern (discovery, gets, writes,
    `watch_resume_synchronous`, breaker attributes) delegates untouched.

    The resume-RV consequence of filtering: a tracker only advances its
    per-GVK RV from events it was shown, so a resumed watch replays the
    interleaved UNOWNED events again — each filtered out again here.
    Correctness is unaffected; the replay cost is bounded by the
    upstream client's own resume window.
    """

    def __init__(self, inner, owns: Callable[[GVK, str], bool]):
        self.inner = inner
        self.owns = owns

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _admit(self, gvk: GVK, obj: dict) -> bool:
        ns = ((obj or {}).get("metadata") or {}).get("namespace") or ""
        return self.owns(tuple(gvk), ns)

    def list(self, gvk: GVK, namespace: Optional[str] = None) -> list[dict]:
        return [o for o in self.inner.list(gvk, namespace)
                if self._admit(gvk, o)]

    def watch(self, gvk: GVK, callback: Callable[[WatchEvent], None],
              send_initial: bool = True, resource_version: str = "",
              on_gap: Optional[Callable[[], None]] = None
              ) -> Callable[[], None]:
        gvk = tuple(gvk)

        def deliver(event: WatchEvent) -> None:
            if self._admit(gvk, event.object):
                callback(event)

        return self.inner.watch(gvk, deliver, send_initial=send_initial,
                                resource_version=resource_version,
                                on_gap=on_gap)


# --------------------------------------------------------------- REST client


def _plural(kind: str) -> str:
    lower = kind.lower()
    if lower.endswith("s") or lower.endswith("x") or lower.endswith("ch"):
        return lower + "es"
    if lower.endswith("y"):
        return lower[:-1] + "ies"
    return lower + "s"


class RestKubeClient:
    """Minimal apiserver REST client (in-cluster or kubeconfig-less;
    production deployments run in-cluster with the mounted service
    account). Same surface as FakeKube minus watch streaming — the watch
    manager polls list+resourceVersion for this client.

    Reference counterpart: controller-runtime's client + discovery
    (vendored k8s client-go in the reference tree).
    """

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 kubeconfig: Optional[str] = None):
        client_cert: Optional[tuple] = None
        if base_url is None and token is None:
            # precedence: an EXPLICIT kubeconfig (argument or $KUBECONFIG)
            # wins unconditionally; otherwise a mounted in-cluster service
            # account wins over the implicit ~/.kube/config default — a
            # pod must talk to its own apiserver, not whatever cluster a
            # baked-in config file happens to point at
            explicit = kubeconfig or os.environ.get("KUBECONFIG")
            in_cluster = os.path.exists(f"{self.SA_DIR}/token")
            if explicit or not in_cluster:
                cfg = self._load_kubeconfig(kubeconfig)
                if cfg is not None:
                    base_url = cfg.get("server")
                    token = cfg.get("token")
                    ca_file = ca_file or cfg.get("ca_file")
                    client_cert = cfg.get("client_cert")
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = base_url or (f"https://{host}:{port}" if host else
                                     "https://kubernetes.default.svc")
        if token is None and os.path.exists(f"{self.SA_DIR}/token"):
            with open(f"{self.SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        ctx = ssl.create_default_context()
        ca = ca_file or f"{self.SA_DIR}/ca.crt"
        if os.path.exists(ca):
            ctx.load_verify_locations(ca)
        else:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if client_cert is not None:
            ctx.load_cert_chain(*client_cert)
        self._ssl = ctx
        self._plurals: dict[GVK, tuple[str, bool]] = {}

    @staticmethod
    def _load_kubeconfig(path: Optional[str]) -> Optional[dict]:
        """Minimal kubeconfig reader: current-context's cluster server,
        CA, and user token/client-cert. Inline *-data fields are
        written to temp files (ssl wants paths)."""
        import base64
        import tempfile

        path = path or os.environ.get("KUBECONFIG") or \
            os.path.expanduser("~/.kube/config")
        if not os.path.exists(path):
            return None
        try:
            import yaml

            with open(path) as f:
                cfg = yaml.safe_load(f) or {}
        except Exception:
            return None

        def by_name(section, name):
            for e in cfg.get(section) or []:
                if e.get("name") == name:
                    return e.get(section[:-1]) or {}
            return {}

        def materialize(data_key, file_key, src):
            if src.get(file_key):
                return src[file_key]
            if src.get(data_key):
                import atexit

                f = tempfile.NamedTemporaryFile(delete=False,
                                                suffix=".pem")
                f.write(base64.b64decode(src[data_key]))
                f.close()
                # key material at 0600, removed on exit
                os.chmod(f.name, 0o600)
                atexit.register(lambda p=f.name:
                                os.path.exists(p) and os.unlink(p))
                return f.name
            return None

        try:
            ctx_name = cfg.get("current-context")
            ctx = by_name("contexts", ctx_name)
            cluster = by_name("clusters", ctx.get("cluster"))
            user = by_name("users", ctx.get("user"))
            out: dict = {"server": cluster.get("server")}
            out["ca_file"] = materialize("certificate-authority-data",
                                         "certificate-authority", cluster)
            out["token"] = user.get("token")
            cert = materialize("client-certificate-data",
                               "client-certificate", user)
            key = materialize("client-key-data", "client-key", user)
            if cert and key:
                out["client_cert"] = (cert, key)
        except Exception:
            # an unreadable/corrupt kubeconfig falls back to in-cluster
            # defaults, it must not crash startup
            return None
        return out if out.get("server") else None

    def _open(self, method: str, path: str, body: Any = None,
              timeout: float = 30):
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, context=self._ssl,
                                      timeout=timeout)

    def _request(self, method: str, path: str, body: Any = None) -> Any:
        # GETs are idempotent: retry transient failures with backoff
        # (client-go's default behavior; a blip must not fail a sweep)
        attempts = 3 if method == "GET" else 1
        for attempt in range(attempts):
            try:
                with self._open(method, path, body) as resp:
                    return json.loads(resp.read() or b"null")
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    raise NotFound(path) from None
                if e.code == 409:
                    raise Conflict(path) from None
                if e.code in (429, 500, 502, 503, 504) and \
                        attempt + 1 < attempts:
                    time.sleep(0.2 * (2 ** attempt))
                    continue
                raise KubeError(f"{method} {path}: HTTP {e.code}",
                                e.code) from None
            except OSError as e:
                if attempt + 1 < attempts:
                    time.sleep(0.2 * (2 ** attempt))
                    continue
                raise KubeError(f"{method} {path}: {e}") from None

    def _resource_path(self, gvk: GVK, namespace: str = "") -> str:
        group, version, kind = gvk
        info = self._plurals.get(tuple(gvk))
        if info is None:
            plural, namespaced = self._discover(gvk)
        else:
            plural, namespaced = info
        prefix = f"/api/{version}" if not group else f"/apis/{group}/{version}"
        if namespaced and namespace:
            return f"{prefix}/namespaces/{namespace}/{plural}"
        return f"{prefix}/{plural}"

    def _discover(self, gvk: GVK) -> tuple[str, bool]:
        group, version, kind = gvk
        path = f"/api/{version}" if not group else f"/apis/{group}/{version}"
        try:
            rl = self._request("GET", path)
            for r in rl.get("resources", []):
                if r.get("kind") == kind and "/" not in r.get("name", ""):
                    out = (r["name"], bool(r.get("namespaced")))
                    self._plurals[tuple(gvk)] = out
                    return out
        except KubeError:
            pass
        out = (_plural(kind), True)
        self._plurals[tuple(gvk)] = out
        return out

    def server_preferred_resources(self) -> list[dict]:
        out = []
        groups = self._request("GET", "/apis").get("groups", [])
        versions = [("", "v1", "/api/v1")]
        for g in groups:
            pv = (g.get("preferredVersion") or {}).get("groupVersion")
            if pv:
                versions.append((g["name"], pv.split("/")[-1], f"/apis/{pv}"))
        for group, version, path in versions:
            try:
                rl = self._request("GET", path)
            except KubeError:
                continue
            for r in rl.get("resources", []):
                if "/" in r.get("name", ""):
                    continue  # subresources
                out.append({"group": group, "version": version,
                            "kind": r.get("kind"),
                            "namespaced": bool(r.get("namespaced")),
                            "verbs": r.get("verbs") or []})
        return out

    def create(self, obj: dict) -> dict:
        meta = obj.get("metadata") or {}
        path = self._resource_path(gvk_of(obj), meta.get("namespace") or "")
        return self._request("POST", path, obj)

    def get(self, gvk: GVK, name: str, namespace: str = "") -> dict:
        return self._request(
            "GET", f"{self._resource_path(gvk, namespace)}/{name}")

    def update(self, obj: dict, subresource: str = "") -> dict:
        meta = obj.get("metadata") or {}
        path = (f"{self._resource_path(gvk_of(obj), meta.get('namespace') or '')}"
                f"/{meta.get('name')}")
        if subresource:
            path += f"/{subresource}"
        return self._request("PUT", path, obj)

    def apply(self, obj: dict) -> dict:
        try:
            return self.create(obj)
        except Conflict:
            meta = obj.setdefault("metadata", {})
            cur = self.get(gvk_of(obj), meta.get("name") or "",
                           meta.get("namespace") or "")
            meta["resourceVersion"] = cur["metadata"]["resourceVersion"]
            return self.update(obj)

    def delete(self, gvk: GVK, name: str, namespace: str = "") -> None:
        self._request(
            "DELETE", f"{self._resource_path(gvk, namespace)}/{name}")

    LIST_PAGE_LIMIT = 500

    def _fill_gvk(self, items: list[dict], gvk: GVK) -> list[dict]:
        group, version, kind = gvk
        api_version = version if not group else f"{group}/{version}"
        for it in items:
            it.setdefault("apiVersion", api_version)
            it.setdefault("kind", kind)
        return items

    def _list_paged(self, gvk: GVK,
                    namespace: str = "") -> tuple[list[dict], str]:
        """Chunked list (?limit + continue tokens) -> (items, list
        resourceVersion) — one giant unpaged list response can stall
        the apiserver on big clusters."""
        base = self._resource_path(gvk, namespace)
        items: list[dict] = []
        cont = ""
        rv = ""
        while True:
            q = f"?limit={self.LIST_PAGE_LIMIT}"
            if cont:
                q += f"&continue={urllib.parse.quote(cont)}"
            rl = self._request("GET", base + q)
            items.extend(rl.get("items") or [])
            meta = rl.get("metadata") or {}
            rv = meta.get("resourceVersion") or rv
            cont = meta.get("continue") or ""
            if not cont:
                break
        return self._fill_gvk(items, gvk), rv

    def list(self, gvk: GVK, namespace: Optional[str] = None) -> list[dict]:
        items, _rv = self._list_paged(gvk, namespace or "")
        return items

    # streamed watches ride long-lived chunked responses; the read
    # timeout must exceed the server's timeoutSeconds or healthy idle
    # streams get cut mid-wait
    WATCH_TIMEOUT_S = 300

    # backoff-relist loop bounds: full jitter on every sleep so an API
    # server blip does not re-synchronize every watcher in the cluster
    # into a thundering re-list herd at t+0.5, t+1, t+2, ...
    WATCH_BACKOFF_BASE_S = 0.5
    WATCH_BACKOFF_CAP_S = 30.0

    def _watch_backoff_wait(self, stop: threading.Event,
                            backoff: float) -> float:
        """Sleep a jittered backoff (uniform in [backoff/2, backoff]);
        returns the next, doubled-and-capped backoff."""
        stop.wait(backoff * (0.5 + random.random() * 0.5))
        return min(backoff * 2, self.WATCH_BACKOFF_CAP_S)

    def watch(self, gvk: GVK, callback, send_initial: bool = True,
              resource_version: str = "", on_gap=None):
        """Streaming watch (?watch=1&resourceVersion=...) with bookmark
        handling and backoff-relist on 410 Gone — client-go informer
        semantics (the dynamiccache fork's underlying ListerWatcher).
        Falls back to poll-and-diff when the server cannot stream
        (e.g. a stub without watch support).

        With resource_version (warm restart: the persisted per-GVK RV),
        the initial paged re-list is SKIPPED and the stream opens at
        that RV — no duplicate ADDED storm for a cluster the caller
        already knows; a successful stream replays everything missed
        while down, deletes included. If the server instead answers 410
        Gone (RV compacted), `on_gap` fires ONCE — the caller schedules
        its own list-diff reconcile for objects deleted in the gap —
        and the standard backoff-relist heals the rest: the diff against
        the empty known-map re-emits every live object and the caller's
        state map dedupes."""
        stop = threading.Event()

        def relist(known: dict, first: bool) -> tuple[dict, str]:
            """Sync state from a fresh list: emit the diff, return the
            new known-map and the list resourceVersion to stream from."""
            items, rv = self._list_paged(gvk)
            seen = {}
            for o in items:
                k = _key(o)
                orv = (o.get("metadata") or {}).get("resourceVersion")
                seen[k] = (orv, o)
                if k not in known:
                    if not first or send_initial:
                        callback(WatchEvent("ADDED", o))
                elif known[k][0] != orv:
                    callback(WatchEvent("MODIFIED", o))
            for k in set(known) - set(seen):
                callback(WatchEvent("DELETED", known[k][1]))
            return seen, rv

        def stream(known: dict, rv: str) -> tuple[dict, str, bool]:
            """One watch connection; returns (known, rv, gone) where
            gone=True means the RV expired (410) and a relist is due."""
            base = self._resource_path(gvk)
            q = (f"?watch=1&allowWatchBookmarks=true"
                 f"&timeoutSeconds={self.WATCH_TIMEOUT_S - 30}"
                 f"&resourceVersion={urllib.parse.quote(rv)}")
            group, version, kind = gvk
            api_version = version if not group else f"{group}/{version}"
            with self._open("GET", base + q,
                            timeout=self.WATCH_TIMEOUT_S) as resp:
                for line in resp:
                    if stop.is_set():
                        return known, rv, False
                    line = line.strip()
                    if not line:
                        continue
                    ev = json.loads(line)
                    etype = ev.get("type")
                    obj = ev.get("object") or {}
                    if etype == "BOOKMARK":
                        rv = (obj.get("metadata") or {}).get(
                            "resourceVersion") or rv
                        continue
                    if etype == "ERROR":
                        if (obj.get("code") == 410
                                or "too old" in str(obj.get("message"))):
                            return known, rv, True
                        raise KubeError(f"watch {gvk}: {obj}")
                    if etype not in ("ADDED", "MODIFIED", "DELETED"):
                        # a server that ignored ?watch=1 (or a corrupt
                        # stream) must resync, not emit junk events
                        raise KubeError(f"watch {gvk}: unexpected "
                                        f"frame {ev!r}")
                    obj.setdefault("apiVersion", api_version)
                    obj.setdefault("kind", kind)
                    k = _key(obj)
                    orv = (obj.get("metadata") or {}).get(
                        "resourceVersion")
                    if etype == "DELETED":
                        known.pop(k, None)
                    else:
                        known[k] = (orv, obj)
                    rv = orv or rv
                    callback(WatchEvent(etype, obj))
            return known, rv, False  # clean server-side timeout close

        def poll_loop(known: dict, first: bool):
            """2s list-and-diff, continuing from the streamed state —
            restarting from empty would duplicate ADDED events and
            never emit DELETED for objects lost in the gap."""
            while not stop.is_set():
                try:
                    known, _rv = relist(known, first)
                    first = False
                except KubeError:
                    pass
                stop.wait(2.0)

        def loop():
            known: dict = {}
            # resume mode: stream straight from the persisted RV (no
            # initial list); first=False so any later gap-heal relist
            # EMITS its diff instead of suppressing it
            first = not resource_version
            rv = resource_version or ""
            need_relist = not resource_version
            # until the resumed stream is confirmed good, any fall into
            # the relist path means events (deletes especially) may have
            # been missed: signal the gap exactly once
            resume_pending = bool(resource_version)
            backoff = self.WATCH_BACKOFF_BASE_S
            bad_frames = 0
            while not stop.is_set():
                try:
                    if need_relist:
                        if resume_pending:
                            resume_pending = False
                            if on_gap is not None:
                                try:
                                    on_gap()
                                except Exception:
                                    pass
                        known, rv = relist(known, first)
                        first = False
                        need_relist = False
                    known, rv, gone = stream(known, rv)
                    backoff = self.WATCH_BACKOFF_BASE_S
                    bad_frames = 0
                    if not gone:
                        resume_pending = False  # server accepted our RV
                    if gone:
                        need_relist = True  # RV expired: resync
                except urllib.error.HTTPError as e:
                    if e.code in (400, 405, 501):
                        # server cannot stream: degrade to polling. A
                        # pending resume dies here — the poll diff
                        # against an empty known-map cannot surface
                        # downtime deletions, so the gap must be
                        # signaled before degrading
                        if resume_pending and on_gap is not None:
                            try:
                                on_gap()
                            except Exception:
                                pass
                        poll_loop(known, first)
                        return
                    need_relist = True
                    backoff = self._watch_backoff_wait(stop, backoff)
                except (KubeError, OSError, ValueError) as e:
                    if isinstance(e, KubeError) and \
                            "unexpected frame" in str(e):
                        # a server answering ?watch=1 with plain lists
                        # can never stream: after a few tries, poll at
                        # the 2s cadence instead of error-backoff
                        bad_frames += 1
                        if bad_frames >= 3:
                            poll_loop(known, first)
                            return
                    need_relist = True
                    backoff = self._watch_backoff_wait(stop, backoff)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return stop.set


# ------------------------------------------------------------ leader election


LEASE_GVK = ("coordination.k8s.io", "v1", "Lease")

_LEASE_TIME_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def _lease_now() -> str:
    import datetime

    return datetime.datetime.utcnow().strftime(_LEASE_TIME_FMT)


def _lease_parse(ts) -> Optional[float]:
    import calendar
    import datetime

    if not ts:
        return None
    for fmt in (_LEASE_TIME_FMT, "%Y-%m-%dT%H:%M:%SZ"):
        try:
            dt = datetime.datetime.strptime(str(ts), fmt)
            return calendar.timegm(dt.timetuple()) + dt.microsecond / 1e6
        except ValueError:
            continue
    return None


class LeaseElector:
    """`coordination.k8s.io/v1` Lease-based leader election.

    Counterpart of controller-runtime's leaderelection (the reference
    runs its audit and status writers under it, main.go's
    --enable-leader-election): acquire-or-takeover with conflict-safe
    updates, periodic renewal at a fraction of the lease duration,
    jittered retry while another holder is live, and a graceful release
    on stop() so failover costs milliseconds instead of a full lease
    timeout. Leadership transitions are logged, exported via the
    gatekeeper_tpu_leader metric, and delivered to the optional
    callbacks; `is_leader` is the gate the audit loop and the
    GuardedKube write fence consult.

    The `kube.lease` fault point (utils/faults.py) simulates theft
    ("steal": a rival identity takes the lease), lapse ("expire": our
    renews stop landing), and renew API failures ("error")."""

    def __init__(self, kube, lease_name: str = "gatekeeper-tpu-leader",
                 namespace: str = "gatekeeper-system",
                 identity: Optional[str] = None,
                 lease_duration: float = 15.0,
                 retry_period: Optional[float] = None,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        from .util import pod_name

        self.kube = kube
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or pod_name()
        self.lease_duration = max(0.1, lease_duration)
        self.retry_period = retry_period if retry_period is not None \
            else max(0.05, self.lease_duration / 3.0)
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._leader = threading.Event()
        self._last_renew = 0.0
        # locally-observed renew tracking (client-go's technique): a
        # rival's lease is "expired" only when (holder, renewTime) has
        # not CHANGED for a lease duration on OUR monotonic clock —
        # comparing the holder's wall-clock renewTime directly would
        # turn inter-node clock skew into premature takeover (dual
        # leaders) or delayed failover
        self._observed: Optional[tuple] = None  # (holder, renew_raw, t)
        self.transitions = 0  # local became/lost count, for tests

    # --------------------------------------------------------------- state

    @property
    def is_leader(self) -> bool:
        return self._leader.is_set()

    def wait_leader(self, timeout: Optional[float] = None) -> bool:
        return self._leader.wait(timeout)

    def healthy(self) -> bool:
        """The elector loop is running (readiness surfaces a dead
        elector; NOT being leader is a normal state, not a failure)."""
        return self._thread is None or self._thread.is_alive() or \
            self._stop.is_set()

    def _become(self, leading: bool, why: str) -> None:
        from . import metrics

        was = self._leader.is_set()
        if was == leading:
            return
        if leading:
            self._leader.set()
        else:
            self._leader.clear()
        self.transitions += 1
        metrics.report_leader(leading)
        _lease_log().info(
            "leadership %s" % ("acquired" if leading else "lost"),
            details={"lease": f"{self.namespace}/{self.lease_name}",
                     "identity": self.identity, "reason": why})
        cb = self.on_started_leading if leading else self.on_stopped_leading
        if cb is not None:
            try:
                cb()
            except Exception as e:
                _lease_log().error("leadership callback failed",
                                   details=str(e))

    # --------------------------------------------------------------- lease

    def _lease_stub(self) -> dict:
        return {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": self.lease_name,
                             "namespace": self.namespace}}

    def _tick(self) -> None:
        from ..utils import faults

        fault = faults.consume("kube.lease", identity=self.identity)
        if fault is not None:
            self._apply_fault(fault)
            if fault[0] in ("error", "raise"):
                raise KubeError("injected fault at kube.lease", code=500)
            if fault[0] == "expire":
                # our renews stopped landing: no renew THIS tick — the
                # lapsed lease sits takeable until the next tick, when
                # we re-contend like any other candidate
                return
        try:
            lease = self.kube.get(LEASE_GVK, self.lease_name,
                                  self.namespace)
        except NotFound:
            self._try_create()
            return
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        renew_raw = spec.get("renewTime")
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration)
        now = time.monotonic()
        if self._observed is None or \
                self._observed[:2] != (holder, renew_raw):
            self._observed = (holder, renew_raw, now)
        expired = renew_raw is None or \
            now - self._observed[2] > duration
        if holder == self.identity:
            self._renew(lease)
        elif not holder or expired:
            self._takeover(lease)
        else:
            # another holder is live: we are (or just became) a follower
            self._become(False, f"lease held by {holder}")

    def _try_create(self) -> None:
        lease = self._lease_stub()
        lease["spec"] = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(max(1, self.lease_duration)),
            "acquireTime": _lease_now(),
            "renewTime": _lease_now(),
            "leaseTransitions": 0,
        }
        try:
            self.kube.create(lease)
        except Conflict:
            return  # raced another candidate; next tick re-evaluates
        self._last_renew = time.monotonic()
        self._become(True, "lease created")

    def _renew(self, lease: dict) -> None:
        lease["spec"]["renewTime"] = _lease_now()
        lease["spec"]["holderIdentity"] = self.identity
        try:
            self.kube.update(lease)
        except Conflict:
            # someone else wrote the lease: re-read next tick; if we
            # were deposed, the holder check will demote us then
            self._check_renew_deadline()
            return
        except KubeError:
            self._check_renew_deadline()
            return
        self._last_renew = time.monotonic()
        self._become(True, "lease renewed")

    def _takeover(self, lease: dict) -> None:
        spec = lease.setdefault("spec", {})
        spec["holderIdentity"] = self.identity
        spec["leaseDurationSeconds"] = int(max(1, self.lease_duration))
        spec["acquireTime"] = _lease_now()
        spec["renewTime"] = _lease_now()
        spec["leaseTransitions"] = int(spec.get("leaseTransitions") or 0) + 1
        try:
            self.kube.update(lease)  # RV-checked: losers Conflict
        except (Conflict, KubeError):
            return
        self._last_renew = time.monotonic()
        self._become(True, "expired lease taken over")

    # a failing leader steps down at this fraction of the lease
    # duration — STRICTLY before the horizon at which rivals may take
    # over (client-go's renewDeadline < leaseDuration), so fenced
    # writes stop before a new leader's writes can start
    RENEW_DEADLINE_FRACTION = 2.0 / 3.0

    def _check_renew_deadline(self) -> None:
        """A leader whose renews keep failing must step down BEFORE the
        lease it last wrote can lapse for everyone else — stepping down
        only at the full duration would leave a window where a rival
        has legitimately taken over while we still pass the write
        fence."""
        deadline = self.lease_duration * self.RENEW_DEADLINE_FRACTION
        if self.is_leader and \
                time.monotonic() - self._last_renew > deadline:
            self._become(False, "renew deadline exceeded")

    def _apply_fault(self, fault: tuple) -> None:
        mode, param = fault
        if mode == "steal":
            # a rival identity takes the lease out from under us
            thief = param or "chaos-rival"
            try:
                lease = self.kube.get(LEASE_GVK, self.lease_name,
                                      self.namespace)
                lease.setdefault("spec", {}).update({
                    "holderIdentity": thief, "renewTime": _lease_now(),
                    "leaseDurationSeconds":
                        int(max(1, self.lease_duration))})
                self.kube.update(lease)
            except KubeError:
                pass
        elif mode == "expire":
            # our renews stopped landing: the lease lapses and we must
            # step down before anyone else can claim it
            try:
                lease = self.kube.get(LEASE_GVK, self.lease_name,
                                      self.namespace)
                if (lease.get("spec") or {}).get("holderIdentity") == \
                        self.identity:
                    lease["spec"]["renewTime"] = \
                        "1970-01-01T00:00:00.000000Z"
                    self.kube.update(lease)
            except KubeError:
                pass
            self._become(False, "lease expired (injected)")

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        from . import metrics

        # export the initial follower state: a replica that never wins
        # must still emit the gauge, or the sum(is_leader="true") != 1
        # alert cannot tell "no leader" from "no metrics"
        metrics.report_leader(self.is_leader)
        self._thread = threading.Thread(target=self._loop, name="elector",
                                        daemon=True)
        self._thread.start()

    def stop(self, release: bool = True) -> None:
        """Graceful shutdown: release the lease when we hold it so the
        survivor fails over immediately instead of waiting out the
        lease duration (release=False simulates a crash in tests)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if release and self.is_leader:
            try:
                lease = self.kube.get(LEASE_GVK, self.lease_name,
                                      self.namespace)
                if (lease.get("spec") or {}).get("holderIdentity") == \
                        self.identity:
                    lease["spec"]["holderIdentity"] = ""
                    self.kube.update(lease)
            except KubeError as e:
                _lease_log().warning("lease release failed",
                                     details=str(e))
        self._become(False, "shutdown")

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:
                _lease_log().warning("leader election tick failed",
                                     details=str(e))
                self._check_renew_deadline()
            # renew fast while leading; retry jittered while following
            # (full jitter: candidates must not stampede the apiserver
            # in lockstep when a leader dies)
            period = self.retry_period
            if not self.is_leader:
                period *= 0.5 + random.random()
            self._stop.wait(period)


def _lease_log():
    from .logging import logger

    return logger("elector")
