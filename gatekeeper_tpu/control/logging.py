"""Structured logging.

Key names mirror the reference pkg/logging/logging.go:3-20 so downstream
log pipelines keyed on those fields keep working; output is JSON lines
(the reference uses zap's JSON encoder in production, main.go:254-269).
"""

from __future__ import annotations

import json
import logging
import sys
import time

# structured keys (reference logging.go)
PROCESS = "process"
DETAILS = "details"
EVENT_TYPE = "event_type"
TEMPLATE_NAME = "template_name"
CONSTRAINT_NAME = "constraint_name"
CONSTRAINT_GROUP = "constraint_group"
CONSTRAINT_API_VERSION = "constraint_api_version"
CONSTRAINT_KIND = "constraint_kind"
CONSTRAINT_ACTION = "constraint_action"
CONSTRAINT_STATUS = "constraint_status"
RESOURCE_GROUP = "resource_group"
RESOURCE_KIND = "resource_kind"
RESOURCE_API_VERSION = "resource_api_version"
RESOURCE_NAMESPACE = "resource_namespace"
RESOURCE_NAME = "resource_name"
REQUEST_USERNAME = "request_username"
DEBUG_LEVEL = 1  # zap's debug verbosity analog


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "level": record.levelname.lower(),
            "ts": round(time.time(), 3),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "structured", None)
        if extra:
            entry.update(extra)
        if record.exc_info:
            entry["error"] = self.formatException(record.exc_info)
        return json.dumps(entry, default=str)


def setup(level: str = "INFO", stream=None) -> None:
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter())
    root = logging.getLogger("gatekeeper")
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.propagate = False


def logger(name: str) -> "StructuredLogger":
    return StructuredLogger(logging.getLogger(f"gatekeeper.{name}"))


class StructuredLogger:
    def __init__(self, base: logging.Logger):
        self._base = base

    def _log(self, level: int, msg: str, kv: dict) -> None:
        self._base.log(level, msg, extra={"structured": kv})

    def info(self, msg: str, **kv) -> None:
        self._log(logging.INFO, msg, kv)

    def debug(self, msg: str, **kv) -> None:
        self._log(logging.DEBUG, msg, kv)

    def warning(self, msg: str, **kv) -> None:
        self._log(logging.WARNING, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._log(logging.ERROR, msg, kv)
