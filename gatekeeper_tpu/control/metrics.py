"""Metrics registry + Prometheus text exposition.

Metric-name parity with the reference's per-package stats reporters
(SURVEY.md §2.1): violations, audit_duration_seconds, audit_last_run_time
(pkg/audit/stats_reporter.go), request_count / request_duration_seconds
(pkg/webhook), constraints (constraint controller), constraint_templates +
ingestion duration (constrainttemplate controller), sync* (sync
controller), watch_manager_* (watch). Exported on --prometheus-port in the
text format (reference pkg/metrics/prometheus_exporter.go:17-43).
"""

from __future__ import annotations

import http.server
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Optional


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind  # counter | gauge | histogram
        self.lock = threading.Lock()
        self.values: dict[tuple, float] = defaultdict(float)
        self.label_names: tuple = ()
        # histogram state
        self.buckets: tuple = ()
        self.bucket_counts: dict[tuple, list] = {}
        self.sums: dict[tuple, float] = defaultdict(float)
        self.counts: dict[tuple, int] = defaultdict(int)
        # OpenMetrics exemplars: per label-set, per bucket index, the
        # LATEST (trace_id, observed value, unix ts) that landed in
        # that bucket — a slow p99 bucket links straight to its
        # /debug/traces flight-recorder entry
        self.exemplars: dict[tuple, dict[int, tuple]] = {}


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, help_: str, kind: str, labels: tuple) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = _Metric(name, help_, kind)
                m.label_names = labels
                self._metrics[name] = m
            return m

    # ------------------------------------------------------------ recorders

    def counter_add(self, name: str, help_: str, value: float = 1.0,
                    **labels) -> None:
        m = self._get(name, help_, "counter", tuple(sorted(labels)))
        with m.lock:
            m.values[_lv(labels)] += value

    def gauge_set(self, name: str, help_: str, value: float, **labels) -> None:
        m = self._get(name, help_, "gauge", tuple(sorted(labels)))
        with m.lock:
            m.values[_lv(labels)] = value

    @staticmethod
    def _freeze_buckets(m: _Metric, buckets) -> None:
        """Pin a histogram's bucket bounds at FIRST registration and
        raise on any later mismatch. Re-assigning per call (the old
        behavior) let two call sites with different bounds silently
        mis-bucket counts against each other's stale lists — the
        rendered cumulative histogram stayed plausible while every
        quantile computed from it was wrong."""
        if not m.buckets:
            m.buckets = tuple(buckets)
        elif m.buckets != tuple(buckets):
            raise ValueError(
                f"histogram {m.name} registered with buckets "
                f"{m.buckets}; observe called with {tuple(buckets)}")

    def observe(self, name: str, help_: str, value: float,
                buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30, 60,
                         300), exemplar: Optional[str] = None,
                **labels) -> None:
        """`exemplar` (a sampled trace id) attaches to the bucket this
        observation lands in; the OpenMetrics exposition renders it so
        a latency bucket links to its flight-recorder trace."""
        m = self._get(name, help_, "histogram", tuple(sorted(labels)))
        with m.lock:
            self._freeze_buckets(m, buckets)
            key = _lv(labels)
            if key not in m.bucket_counts:
                m.bucket_counts[key] = [0] * (len(buckets) + 1)
            counts = m.bucket_counts[key]
            idx = len(buckets)  # +Inf overflow by default
            for i, b in enumerate(buckets):
                if value <= b:
                    idx = i
                    break
            counts[idx] += 1
            m.sums[key] += value
            m.counts[key] += 1
            if exemplar:
                m.exemplars.setdefault(key, {})[idx] = (
                    str(exemplar), value, time.time())

    def observe_bucketed(self, name: str, help_: str, buckets: tuple,
                         bucket_counts: list, sum_: float, count: int,
                         **labels) -> None:
        """Merge a PRE-AGGREGATED histogram delta. `bucket_counts` must
        carry len(buckets)+1 entries — the last is the +Inf overflow
        bucket — and `count` must equal their sum, or the rendered
        cumulative histogram goes invalid (+Inf bucket < _count). The
        backplane frontends run in their own processes and ship their
        forward-latency histograms over the wire as aggregated deltas —
        replaying observations one by one would cost more than the
        latency being measured."""
        m = self._get(name, help_, "histogram", tuple(sorted(labels)))
        with m.lock:
            self._freeze_buckets(m, buckets)
            key = _lv(labels)
            if key not in m.bucket_counts:
                m.bucket_counts[key] = [0] * (len(buckets) + 1)
            counts = m.bucket_counts[key]
            for i, c in enumerate(bucket_counts[: len(counts)]):
                counts[i] += c
            m.sums[key] += sum_
            m.counts[key] += count

    # ----------------------------------------------------------- snapshot

    def snapshot(self, names: tuple) -> dict:
        """JSON-safe totals of the named metrics, for the N-engine
        plane's stats relay: an engine child answers the primary's poll
        with this, the primary diffs against the previous poll and
        merges the deltas into ITS registry — so /metrics on the
        primary aggregates shed counts, decisions, cache outcomes, and
        per-engine stage histograms across every engine process."""
        out = {}
        with self._lock:
            metrics = [self._metrics[n] for n in names
                       if n in self._metrics]
        for m in metrics:
            with m.lock:
                ent = {"kind": m.kind, "help": m.help,
                       "labels": list(m.label_names)}
                if m.kind in ("counter", "gauge"):
                    ent["values"] = [[list(k), v]
                                     for k, v in m.values.items()]
                else:
                    ent["buckets"] = list(m.buckets)
                    ent["hist"] = [
                        [list(k), list(m.bucket_counts[k]),
                         m.sums[k], m.counts[k]]
                        for k in m.bucket_counts]
                out[m.name] = ent
        return out

    def merge_snapshot_delta(self, cur: dict, prev: dict) -> None:
        """Merge (cur - prev) of a Registry.snapshot() into this
        registry. A restarted engine's counters reset to zero — any
        negative delta treats cur as the whole delta (counts since the
        restart are new work, not a rewind)."""
        prev = prev or {}
        for name, ent in cur.items():
            labels = tuple(ent.get("labels") or ())
            pent = prev.get(name) or {}
            if ent["kind"] == "gauge":
                # gauges merge by LAST VALUE, not delta — the relayed
                # series carry engine/worker labels so each child owns
                # its own series, and a delta-add would turn any dip
                # (queue draining, duty decaying) into garbage
                for k, v in ent.get("values") or []:
                    self.gauge_set(name, ent.get("help", ""), v,
                                   **dict(zip(labels, tuple(k))))
            elif ent["kind"] == "counter":
                pvals = {tuple(k): v
                         for k, v in (pent.get("values") or [])}
                for k, v in ent.get("values") or []:
                    kt = tuple(k)
                    d = v - pvals.get(kt, 0)
                    if d < 0:
                        d = v
                    if d:
                        self.counter_add(name, ent.get("help", ""), d,
                                         **dict(zip(labels, kt)))
            else:
                phist = {tuple(k): (c, s, n)
                         for k, c, s, n in (pent.get("hist") or [])}
                buckets = tuple(ent.get("buckets") or ())
                for k, counts, sum_, n in ent.get("hist") or []:
                    kt = tuple(k)
                    pc, ps, pn = phist.get(kt, ([0] * len(counts),
                                                0.0, 0))
                    dn = n - pn
                    if dn < 0:  # engine restarted
                        dc, ds, dn = list(counts), sum_, n
                    else:
                        dc = [c - p for c, p in zip(counts, pc)]
                        ds = sum_ - ps
                    if dn:
                        self.observe_bucketed(
                            name, ent.get("help", ""), buckets, dc,
                            ds, dn, **dict(zip(labels, kt)))

    # ------------------------------------------------------------- render

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition; `openmetrics=True` renders the
        OpenMetrics dialect instead: per-bucket EXEMPLARS
        (`... # {trace_id="<id>"} <value> <ts>`), the terminating
        `# EOF`, and SPEC-COMPLIANT counter naming — OpenMetrics
        requires counter samples to be `<family>_total` with the
        family (HELP/TYPE) named WITHOUT the suffix, and strict
        scrapers (Prometheus's openmetrics parser included) reject the
        whole exposition otherwise. Our `*_total` counters keep their
        sample names (family drops the suffix); legacy reference-named
        counters (`request_count`, ...) gain `_total` on the sample in
        this dialect only. The plain text format is byte-identical to
        what it always was — exemplars and the renames exist only in
        the negotiated dialect."""
        out = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            name = m.name
            if openmetrics and m.kind == "counter":
                family = name[:-6] if name.endswith("_total") else name
                sample = family + "_total"
            else:
                family = sample = name
            out.append(f"# HELP {family} {m.help}")
            out.append(f"# TYPE {family} {m.kind}")
            with m.lock:
                if m.kind in ("counter", "gauge"):
                    for key, v in sorted(m.values.items()):
                        out.append(f"{sample}{_fmt(m.label_names, key)} {_num(v)}")
                else:
                    for key in sorted(m.bucket_counts):
                        ex = m.exemplars.get(key, {}) if openmetrics \
                            else {}
                        cum = 0
                        for i, b in enumerate(m.buckets):
                            cum += m.bucket_counts[key][i]
                            out.append(
                                f"{m.name}_bucket"
                                f"{_fmt(m.label_names, key, le=_num(b))} {cum}"
                                + _exemplar_suffix(ex.get(i)))
                        cum += m.bucket_counts[key][-1]
                        out.append(
                            f"{m.name}_bucket"
                            f"{_fmt(m.label_names, key, le='+Inf')} {cum}"
                            + _exemplar_suffix(ex.get(len(m.buckets))))
                        out.append(
                            f"{m.name}_sum{_fmt(m.label_names, key)} "
                            f"{_num(m.sums[key])}")
                        out.append(
                            f"{m.name}_count{_fmt(m.label_names, key)} "
                            f"{m.counts[key]}")
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


def _lv(labels: dict) -> tuple:
    return tuple(str(labels[k]) for k in sorted(labels))


def _esc(v) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline). Without it one kind name carrying a quote breaks every
    scraper parsing the whole exposition."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(names: tuple, values: tuple, **extra) -> str:
    pairs = [f'{n}="{_esc(v)}"' for n, v in zip(names, values)]
    pairs += [f'{k}="{v}"' for k, v in extra.items()]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _num(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


def _exemplar_suffix(ex: Optional[tuple]) -> str:
    """OpenMetrics exemplar clause for one bucket sample line (empty
    when the bucket never saw a sampled observation)."""
    if not ex:
        return ""
    trace_id, value, ts = ex
    return f' # {{trace_id="{_esc(trace_id)}"}} {_num(value)} {ts:.3f}'


REGISTRY = Registry()

# OpenMetrics content negotiation: the media type a scraper sends in
# Accept to request the exemplar-bearing dialect, and what we answer
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
TEXT_CONTENT_TYPE = "text/plain; version=0.0.4"


def negotiate_exposition(accept_header: Optional[str],
                         registry: Registry = REGISTRY
                         ) -> tuple[bytes, str]:
    """(body, content_type) for one /metrics scrape, honoring the
    Accept header: `application/openmetrics-text` gets the OpenMetrics
    dialect (bucket exemplars + # EOF); everything else gets the
    classic text format."""
    om = bool(accept_header) and \
        "application/openmetrics-text" in accept_header
    body = registry.render(openmetrics=om).encode()
    return body, OPENMETRICS_CONTENT_TYPE if om else TEXT_CONTENT_TYPE


# ------------------------------------------------- process self-metrics

_PROCESS_START_TIME: Optional[float] = None
_GC_SEEN: dict[tuple, float] = {}
# serializes the GC-delta read-modify-write: the exposition server is
# threaded, and two concurrent scrapes would both read the stale seen
# value and double-add the delta — permanently overcounting exactly
# the counters meant to prove gc.freeze held
_GC_SEEN_LOCK = threading.Lock()


def _process_start_time() -> float:
    """Unix timestamp of process start: /proc/self/stat field 22 (clock
    ticks since boot) + /proc/stat btime, the same derivation the
    official prometheus clients use; import time of this module as the
    fallback off Linux."""
    global _PROCESS_START_TIME
    if _PROCESS_START_TIME is not None:
        return _PROCESS_START_TIME
    try:
        with open("/proc/self/stat") as f:
            # comm may contain spaces/parens: fields start after ')'
            fields = f.read().rpartition(")")[2].split()
        ticks = float(fields[19])  # starttime is field 22 overall
        with open("/proc/stat") as f:
            btime = next(float(line.split()[1]) for line in f
                         if line.startswith("btime "))
        hz = os.sysconf("SC_CLK_TCK")
        _PROCESS_START_TIME = btime + ticks / hz
    except Exception:
        _PROCESS_START_TIME = _IMPORT_TIME
    return _PROCESS_START_TIME


def update_process_metrics(registry: Registry = REGISTRY) -> None:
    """Refresh the standard process/runtime self-metrics. Called on
    every scrape (the values are reads of /proc and gc state, not
    accumulation): process start time, RSS, open FDs, thread count,
    and Python GC generation counts + collection totals — the GC
    series exist specifically to PROVE the serving processes'
    gc.freeze tuning held (a frozen heap shows near-zero gen-2
    collections under load)."""
    import gc

    registry.gauge_set("process_start_time_seconds",
                       "Start time of the process since unix epoch in "
                       "seconds", _process_start_time())
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        registry.gauge_set("process_resident_memory_bytes",
                           "Resident memory size in bytes",
                           rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        pass
    try:
        registry.gauge_set("process_open_fds",
                           "Number of open file descriptors",
                           len(os.listdir("/proc/self/fd")))
    except Exception:
        pass
    registry.gauge_set("process_threads",
                       "Live Python threads in this process",
                       threading.active_count())
    for gen, count in enumerate(gc.get_count()):
        registry.gauge_set("python_gc_objects_tracked",
                           "Objects tracked by the Python GC per "
                           "generation (collection pressure; stays "
                           "flat when gc.freeze held)",
                           count, generation=str(gen))
    for gen, stats in enumerate(gc.get_stats()):
        for field, metric, help_ in (
                ("collections", "python_gc_collections_total",
                 "GC collections per generation"),
                ("collected", "python_gc_objects_collected_total",
                 "Objects collected by the GC per generation")):
            cur = float(stats.get(field, 0))
            key = (metric, gen)
            with _GC_SEEN_LOCK:
                delta = cur - _GC_SEEN.get(key, 0.0)
                _GC_SEEN[key] = cur
            if delta > 0:
                registry.counter_add(metric, help_, delta,
                                     generation=str(gen))


_IMPORT_TIME = time.time()


# ------------------------------------------------- saturation probes

# scrape-time gauge refreshers: each is a zero-arg callable that sets
# its own gauges (queue depths, duty cycles, stream backlog) against
# the live objects it closed over — sampled state, not accumulation,
# so refreshing per scrape is both cheap and always current. Probes
# must never fail a scrape; errors are swallowed per probe.
_SATURATION_PROBES: dict[str, Callable[[], None]] = {}
_PROBES_LOCK = threading.Lock()


def register_saturation_probe(name: str, probe) -> None:
    """Install (or replace) a named scrape-time gauge refresher."""
    with _PROBES_LOCK:
        _SATURATION_PROBES[name] = probe


def unregister_saturation_probe(name: str) -> None:
    with _PROBES_LOCK:
        _SATURATION_PROBES.pop(name, None)


def run_saturation_probes() -> None:
    """Refresh every registered saturation gauge (called on each
    /metrics scrape, and directly by tests)."""
    with _PROBES_LOCK:
        probes = list(_SATURATION_PROBES.values())
    for probe in probes:
        try:
            probe()
        except Exception:
            pass  # a dead probe must never fail the scrape


def serve(port: int, registry: Registry = REGISTRY, addr: str = "",
          debug_providers: Optional[dict] = None
          ) -> http.server.ThreadingHTTPServer:
    """Start the /metrics endpoint (reference --prometheus-port 8888).

    `debug_providers` maps endpoint names to callables taking the raw
    query string and returning a JSON-serializable object; each is
    served at /debug/<name> (the flight recorder dump, per-template
    compile state, and the device-profile armer ride here)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            path, _, query = self.path.partition("?")
            path = path.rstrip("/")
            if path in ("", "/metrics"):
                update_process_metrics(registry)
                run_saturation_probes()
                body, ctype = negotiate_exposition(
                    self.headers.get("Accept"), registry)
                self._reply(200, body, ctype)
                return
            if path.startswith("/debug/") and debug_providers:
                body, status = render_debug(
                    debug_providers, path[len("/debug/"):], query)
                self._reply(status, body, "application/json")
                return
            self.send_response(404)
            self.end_headers()

        def _reply(self, status: int, body: bytes, ctype: str):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer((addr, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server


def render_debug(providers: dict, name: str, query: str
                 ) -> tuple[bytes, int]:
    """Shared /debug/<name> dispatch (the metrics server and the
    health server both mount the same provider registry): returns
    (json_body, http_status). Provider errors answer 500 with the
    error text instead of dropping the connection."""
    from . import jsonio

    provider = providers.get(name)
    if provider is None:
        return (jsonio.dumps_bytes(
            {"error": f"unknown debug endpoint {name!r}",
             "available": sorted(providers)}), 404)
    try:
        return jsonio.dumps_bytes(provider(query)), 200
    except Exception as e:
        return jsonio.dumps_bytes({"error": str(e)}), 500


# convenience recorders with reference metric names


def report_violations(action: str, count: int) -> None:
    REGISTRY.gauge_set("violations", "Total violations detected by the last "
                       "audit run", count, enforcement_action=action)


def report_audit_duration(seconds: float) -> None:
    REGISTRY.observe("audit_duration_seconds", "Latency of audit operation",
                     seconds)


def report_audit_last_run(ts: Optional[float] = None) -> None:
    REGISTRY.gauge_set("audit_last_run_time", "Timestamp of last audit run",
                       ts if ts is not None else time.time())


# which engine process this registry lives in ("0" = the primary /
# in-process engine; "1".. = spawned engine children; "" = a process
# that serves no admission engine). Stamped into the per-engine stage
# and request metrics so an N-engine plane decomposes per chip.
_ENGINE_ID = ""


def set_engine_id(engine_id: str) -> None:
    global _ENGINE_ID
    _ENGINE_ID = str(engine_id)


def engine_id() -> str:
    return _ENGINE_ID


def report_request(admission_status: str, seconds: float) -> None:
    REGISTRY.counter_add("request_count", "Count of admission requests",
                         admission_status=admission_status)
    REGISTRY.observe("request_duration_seconds",
                     "Latency of admission requests", seconds,
                     admission_status=admission_status)
    if _ENGINE_ID:
        # per-engine decomposition of the same counter: the aggregate
        # stays label-compatible with every dashboard built on it while
        # the N-engine plane remains attributable per chip
        REGISTRY.counter_add("gatekeeper_tpu_engine_requests_total",
                             "Admission requests decided, by owning "
                             "engine process",
                             admission_status=admission_status,
                             engine=_ENGINE_ID)


# batch economics: how full micro-batches seal and WHY they sealed.
# A plane whose seals are reason=deadline|max_wait at fill ~0.01 is
# edge-bound (trickle traffic never fills a batch); reason=full at
# fill 1.0 means the engine is the bottleneck and batching is earning
# its latency cost.
FILL_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)

BATCH_SEAL_REASONS = ("deadline", "full", "max_wait", "drain")


def report_batch_seal(reason: str, fill: float,
                      plane: str = "admission") -> None:
    """One sealed micro-batch: `reason` says what closed the collection
    window (full = max_batch reached, deadline = a member's propagated
    deadline forced an early seal, max_wait = the window elapsed,
    drain = shutdown flush), `fill` is len(batch)/max_batch. `plane`
    separates the validating and mutating batchers — the edge-vs-
    engine attribution read must not mix a mutation trickle's
    near-empty seals into the admission series."""
    if reason not in BATCH_SEAL_REASONS:
        reason = "max_wait"
    REGISTRY.counter_add("gatekeeper_tpu_batch_seal_total",
                         "Sealed micro-batches by seal reason and plane",
                         reason=reason, plane=plane)
    REGISTRY.observe("gatekeeper_tpu_batch_fill_ratio",
                     "Fill ratio (members / max_batch) of each sealed "
                     "micro-batch, by plane", min(1.0, max(0.0, fill)),
                     buckets=FILL_BUCKETS, plane=plane)


def report_queue_depth(queue: str, depth: int,
                       engine: str = "") -> None:
    """Sampled depth of one serving-plane queue (scrape-time gauge):
    admission / mutation = MicroBatcher in-flight (queued + sealed +
    flushing, the --admission-max-queue bound's own counter),
    backplane_engine = engine-side evaluations in flight. `engine`
    distinguishes co-resident BackplaneEngine instances — without it
    two engines in one process would overwrite one series and one
    engine's teardown zero would hide the other's live backlog."""
    REGISTRY.gauge_set("gatekeeper_tpu_queue_depth",
                       "Sampled serving-plane queue depth by queue "
                       "(and owning engine, where applicable)",
                       depth, queue=queue, engine=engine)


def report_backplane_inflight(worker: str, inflight: int) -> None:
    """Per-frontend forwarded-and-unanswered review count (shipped in
    the frontend's S-frame stats): the router's least-load signal,
    exported so 'one frontend saturated, others idle' is readable."""
    REGISTRY.gauge_set("gatekeeper_tpu_backplane_inflight",
                       "Reviews a frontend has forwarded over the "
                       "backplane and not yet had answered",
                       inflight, worker=worker)


def report_duty_cycle(duty: float, engine: Optional[str] = None) -> None:
    """Busy-fraction EMA of one engine's evaluator (device sweeps +
    batched admission evals + interpreter fallback, from the driver's
    eval wall clock): ~0 while admission p99 climbs means the EDGE is
    saturated, not the engine — the attribution ROADMAP item 5 needs."""
    REGISTRY.gauge_set("gatekeeper_tpu_device_duty_cycle",
                       "Fraction of wall clock this engine's evaluator "
                       "spent busy (EMA over scrape intervals)",
                       min(1.0, max(0.0, duty)),
                       engine=engine if engine is not None else _ENGINE_ID
                       or "0")


def zero_engine_gauges(engine: str) -> None:
    """Zero one engine child's relayed engine-labeled saturation
    gauges (queue depth, duty cycle). The child's own registry dies
    with its process, but the PRIMARY keeps the last relayed values —
    a dead engine would export its final (possibly saturated) depth
    and duty until its respawn's first stats poll, or forever if the
    respawn keeps failing. The EngineSupervisor calls this on death
    detection and at stop (the PR 13 stale-export discipline applied
    to the relay path)."""
    for queue in ("admission", "mutation", "backplane_engine"):
        report_queue_depth(queue, 0, engine=str(engine))
    report_duty_cycle(0.0, engine=str(engine))


def report_stream_pending(pending: int) -> None:
    """Streaming-audit backlog: tracker dirty keys buffered ahead of
    the next flush (refreshed per flush AND per scrape) — growth here
    means detection latency is about to follow."""
    REGISTRY.gauge_set("gatekeeper_tpu_audit_stream_pending_events",
                       "Watch events buffered (dirty keys) ahead of the "
                       "next streaming-audit flush", pending)


def report_build_info() -> None:
    """The standard build-info join gauge, emitted once at boot:
    version/jax/platform/device-count as labels, value always 1."""
    from .. import __version__
    jax_version = platform = "unknown"
    device_count = 0
    try:
        import jax
        jax_version = getattr(jax, "__version__", "unknown")
        platform = jax.default_backend()
        device_count = len(jax.devices())
    except Exception:
        pass
    REGISTRY.gauge_set("gatekeeper_tpu_build_info",
                       "Build/runtime identity (value is always 1; the "
                       "labels are the payload — join dashboards on "
                       "them)", 1.0,
                       version=__version__, jax_version=jax_version,
                       platform=platform, device_count=str(device_count))


def report_batch_timeout(n: int = 1) -> None:
    """A MicroBatcher.submit() waiter gave up before its batch flushed
    (the entry is dropped from the queue so the flush never evaluates a
    request nobody is waiting for)."""
    REGISTRY.counter_add("admission_batch_timeouts",
                         "Admission requests that timed out waiting for "
                         "their micro-batch to flush", n)


def report_admission_shed(n: int = 1) -> None:
    """A submit() was refused at enqueue time: the micro-batch queue was
    at --admission-max-queue depth, so the request was answered per the
    failure stance immediately instead of queueing into certain
    timeout."""
    REGISTRY.counter_add("admission_requests_shed_total",
                         "Admission requests shed by the bounded "
                         "micro-batch queue", n)


# bounded label-value sets: every reporter that takes a label value
# from a caller folds unknowns to a stable bucket, so a bug (or a
# version-skewed peer over the wire) can never mint an unbounded
# series set — the registry never forgets a label set. Enforced
# statically by gklint's metrics_hygiene checker.
DECISION_CACHE_OUTCOMES = ("hit", "miss", "bypass")
RING_PATHS = ("ring", "inline")
ADAPTIVE_KNOBS = ("batch_max_wait", "batch_max_batch", "shed_depth",
                  "engine_fanout", "prewarm")
ADAPTIVE_DIRECTIONS = ("up", "down", "restore")
DEGRADATION_RUNGS = ("normal", "tighten_shed", "cache_only",
                     "fail_stance")
KUBE_WRITE_OUTCOMES = ("ok", "retried_ok", "failed", "breaker_open",
                       "budget_exhausted", "not_leader")
INGESTION_STATUSES = ("ok", "error", "active")
DEMOTION_REASONS = ("audit-eval", "review-eval", "join-eval",
                    "lowering", "join-lowering")
COMPILE_OUTCOMES = ("ok", "error")
AUDIT_SWEEP_PATHS = ("incremental", "full_resync", "full", "stream")
MATERIALIZE_PATHS = ("vectorized", "exact", "capped")
CACHE_OUTCOMES = ("hit", "miss")
STREAM_FLUSH_OUTCOMES = ("ok", "error", "skipped")
PREVIEW_OUTCOMES = ("ok", "error", "invalid")
SNAPSHOT_OUTCOMES = ("ok", "error", "missing", "fallback")
SCAN_OUTCOMES = ("allow", "deny", "error", "dedup", "skip")
SCAN_TIERS = ("inproc", "backplane", "grpc")

LABEL_FOLD = "other"


def report_adaptive_actuation(knob: str, direction: str,
                              n: int = 1) -> None:
    """One adaptive-controller knob movement (or a kill-switch
    baseline restore): which declared knob moved and which way. The
    aggregate oscillation read — sustained up/down alternation on one
    knob is the flip-flop the bench gate forbids."""
    if knob not in ADAPTIVE_KNOBS:
        knob = LABEL_FOLD
    if direction not in ADAPTIVE_DIRECTIONS:
        direction = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_adaptive_actuations_total",
                         "Adaptive-controller actuations by knob and "
                         "direction", n, knob=knob, direction=direction)


def report_adaptive_knob(knob: str, value: float) -> None:
    """Current value of one adaptive-controller knob (set on every
    actuation and at arm/restore): the convergence read — CI and the
    bench compare this against the hand-tuned optimum."""
    if knob not in ADAPTIVE_KNOBS:
        knob = LABEL_FOLD
    REGISTRY.gauge_set("gatekeeper_tpu_adaptive_knob_value",
                       "Current value of each adaptive-controller "
                       "knob", float(value), knob=knob)


def report_degradation_rung(rung: int) -> None:
    """Current degradation-ladder rung, twice over: a plain gauge
    (0=normal .. 3=fail_stance, the alerting read) and a per-rung
    transition counter (how often the plane ENTERED each rung)."""
    idx = min(len(DEGRADATION_RUNGS) - 1, max(0, int(rung)))
    name = DEGRADATION_RUNGS[idx]
    if name not in DEGRADATION_RUNGS:
        name = LABEL_FOLD  # unreachable; keeps the fold discipline
    REGISTRY.gauge_set("gatekeeper_tpu_degradation_rung",
                       "Current degradation-ladder rung (0=normal, "
                       "1=tighten_shed, 2=cache_only, 3=fail_stance)",
                       idx)
    REGISTRY.counter_add("gatekeeper_tpu_degradation_transitions_total",
                         "Degradation-ladder entries by rung",
                         rung=name)


def report_decision_cache(outcome: str, n: int = 1) -> None:
    """One admission decision-cache consultation: hit (verdict served
    without evaluation), miss (evaluated and cached), or bypass (the
    request is uncacheable — traced, or a deny under --log-denies where
    every denial must re-log)."""
    if outcome not in DECISION_CACHE_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_admission_decision_cache_total",
                         "Admission decision cache lookups by outcome",
                         n, outcome=outcome)


def report_admission_workers(configured: int, connected: int) -> None:
    """Serving-plane topology gauge: --admission-workers as configured
    and the number of frontend processes currently connected to the
    engine backplane (equal when the plane is healthy)."""
    REGISTRY.gauge_set("gatekeeper_tpu_admission_workers",
                       "Admission frontend worker processes",
                       configured, state="configured")
    REGISTRY.gauge_set("gatekeeper_tpu_admission_workers",
                       "Admission frontend worker processes",
                       connected, state="connected")


def report_admission_engines(configured: int, alive: int) -> None:
    """N-engine plane topology gauge: --admission-engines as configured
    and the number of engine processes currently alive (the in-process
    engine counts; a crashed child dips this until its respawn)."""
    REGISTRY.gauge_set("gatekeeper_tpu_admission_engines",
                       "Admission engine processes (one per chip)",
                       configured, state="configured")
    REGISTRY.gauge_set("gatekeeper_tpu_admission_engines",
                       "Admission engine processes (one per chip)",
                       alive, state="alive")


# chaos & recovery: the orchestrator's recovery clock and the
# supervisors' respawn-storm rate limiting, exported as data so MTTR
# is a dashboard read, not a log grep. Bounded label sets with the
# fold discipline like every other enumerated label.
RECOVERY_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0,
                    60.0, 120.0)
CHAOS_COMPONENTS = ("frontend", "engine", "audit_shard", "leader",
                    "apiserver", "backplane", "state")
CHAOS_FAULT_KINDS = ("kill", "pause", "death", "wedge", "flap", "wire",
                     "corrupt", "disk")
SUPERVISOR_KINDS = ("frontend", "engine", "audit")


def report_fault_recovery(component: str, fault: str,
                          seconds: float) -> None:
    """One completed recovery: the wall clock from fault detection (a
    child seen dead/wedged, a flap's first error) to the plane healthy
    again (respawned AND resynced / breaker closed). The MTTR series
    bench config 15 distills into its headline matrix."""
    if component not in CHAOS_COMPONENTS:
        component = LABEL_FOLD
    if fault not in CHAOS_FAULT_KINDS:
        fault = LABEL_FOLD
    REGISTRY.observe("gatekeeper_tpu_fault_recovery_seconds",
                     "Fault-to-recovered wall clock by component and "
                     "fault kind", seconds, buckets=RECOVERY_BUCKETS,
                     component=component, fault=fault)


def report_respawn_backoff(supervisor: str, seconds: float) -> None:
    """Current respawn-backoff delay one supervisor is holding (0 when
    its children are healthy): a sustained non-zero value is a child
    stuck in a crash loop, rate-limited instead of hot-looping."""
    if supervisor not in SUPERVISOR_KINDS:
        supervisor = LABEL_FOLD
    REGISTRY.gauge_set("gatekeeper_tpu_respawn_backoff_seconds",
                       "Current jittered-exponential respawn delay per "
                       "supervisor (0 = healthy)", seconds,
                       supervisor=supervisor)


def report_crashloop_breaker(supervisor: str, tripped: bool) -> None:
    """Crash-loop breaker state per supervisor: 1 after a child has
    died CRASHLOOP_TRIP consecutive times faster than the healthy
    threshold (respawns continue at the capped delay); back to 0 once
    a child survives past it."""
    if supervisor not in SUPERVISOR_KINDS:
        supervisor = LABEL_FOLD
    REGISTRY.gauge_set("gatekeeper_tpu_crashloop_breaker",
                       "1 while a supervisor's child is in a detected "
                       "crash loop (respawn delay capped)",
                       1.0 if tripped else 0.0, supervisor=supervisor)


def zero_supervisor_gauges(supervisor: str) -> None:
    """Teardown zeroing for the supervisor-labeled chaos gauges (the
    PR 13 stale-export discipline): a stopped supervisor must not
    export its last backoff/breaker state forever."""
    report_respawn_backoff(supervisor, 0.0)
    report_crashloop_breaker(supervisor, False)


def gauge_series(name: str) -> dict[tuple, float]:
    """Label-values-tuple -> current value for one gauge family (empty
    when the family never registered). The chaos verifier's stale-gauge
    invariant reads the gklint lifecycle families through this after
    teardown: every series must be zero."""
    m = REGISTRY._metrics.get(name)
    if m is None:
        return {}
    with m.lock:
        return dict(m.values)


# counters/histograms an engine child relays to the primary over the
# backplane M frame (all monotonic — the delta merge assumes it), so
# shed accounting, decision counts, cache outcomes, and per-engine
# stage histograms stay GLOBAL on the primary's /metrics across every
# engine process
ENGINE_RELAY_METRICS = (
    "request_count",
    "request_duration_seconds",
    "admission_requests_shed_total",
    "admission_batch_timeouts",
    "gatekeeper_tpu_admission_decision_cache_total",
    "gatekeeper_tpu_engine_requests_total",
    "gatekeeper_tpu_stage_duration_seconds",
    "gatekeeper_tpu_traces_total",
    # batch economics relay so per-chip seal reasons / fill ratios
    # aggregate on the primary's /metrics like every other counter
    "gatekeeper_tpu_batch_seal_total",
    "gatekeeper_tpu_batch_fill_ratio",
    # saturation GAUGES (engine-/worker-labeled series, merged by
    # last-value): per-chip duty cycle and queue depth must read off
    # the primary's one scrape, not just for engine 0
    "gatekeeper_tpu_queue_depth",
    "gatekeeper_tpu_device_duty_cycle",
    "gatekeeper_tpu_backplane_inflight",
    # frontends ship S-frame deltas to whichever engine answers; a
    # child that received them relays the merged result up
    "gatekeeper_tpu_backplane_forward_duration_seconds",
    "gatekeeper_tpu_backplane_errors_total",
    # shm-ring path counts (counter) + per-frontend request-ring fill
    # (gauge, merged by last value like the other saturation gauges)
    "gatekeeper_tpu_backplane_ring_total",
    "gatekeeper_tpu_backplane_ring_fill_ratio",
)


def engine_stats_snapshot() -> dict:
    """What an engine child answers the primary's M-frame poll with."""
    return REGISTRY.snapshot(ENGINE_RELAY_METRICS)


def merge_engine_stats(cur: dict, prev: dict) -> None:
    """Primary-side merge of one engine child's polled totals."""
    REGISTRY.merge_snapshot_delta(cur, prev)


# frontends bucket their forward latencies locally with these bounds and
# ship aggregated deltas over the backplane stats frame
FORWARD_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 5.0)


def report_backplane_forward(worker: str, bucket_counts: list,
                             sum_: float, count: int) -> None:
    """Merge one frontend's forward-latency histogram delta (enqueue on
    the frontend to verdict bytes received back over the backplane)."""
    REGISTRY.observe_bucketed(
        "gatekeeper_tpu_backplane_forward_duration_seconds",
        "Frontend-observed latency of one review forwarded over the "
        "backplane to the engine and answered", FORWARD_BUCKETS,
        bucket_counts, sum_, count, worker=worker)


def report_backplane_error(worker: str, n: int = 1) -> None:
    REGISTRY.counter_add(
        "gatekeeper_tpu_backplane_errors_total",
        "Reviews a frontend answered per the failure stance because the "
        "engine backplane was unreachable", n, worker=worker)


def report_backplane_ring(worker: str, path: str, n: int = 1) -> None:
    """Shared-memory ring usage per forwarded review: path=ring means
    the review crossed the backplane as a descriptor (zero payload
    copies on the socket), path=inline means it fell back to the
    payload frame (ring exhausted by a burst, oversized review, or the
    engine declined the attach). A rising inline share under load is
    the 'grow --admission-shm-ring-mb' signal."""
    if path not in RING_PATHS:
        path = LABEL_FOLD
    REGISTRY.counter_add(
        "gatekeeper_tpu_backplane_ring_total",
        "Backplane forwards by payload path (ring descriptor vs inline "
        "fallback)", n, worker=worker, path=path)


def report_ring_fill(worker: str, fill: float) -> None:
    """Sampled used fraction of one frontend's request ring (shipped
    in its S-frame stats; zeroed when the frontend's connection dies).
    Sustained values near the allocation watermark mean bursts are
    spilling to the inline path."""
    REGISTRY.gauge_set(
        "gatekeeper_tpu_backplane_ring_fill_ratio",
        "Used fraction of a frontend's request ring (sampled per stats "
        "interval)", min(1.0, max(0.0, fill)), worker=worker)


_BREAKER_STATES = {"closed": 0, "half_open": 1, "open": 2}


def report_breaker(name: str, state: str) -> None:
    """Circuit breaker state gauge (0=closed, 1=half-open, 2=open) plus
    a transition counter so open->half-open->close cycles stay visible
    after the fact."""
    REGISTRY.gauge_set("gatekeeper_tpu_circuit_breaker_state",
                       "Circuit breaker state (0=closed, 1=half-open, "
                       "2=open)", _BREAKER_STATES.get(state, 2),
                       breaker=name)
    REGISTRY.counter_add("gatekeeper_tpu_circuit_breaker_transitions_total",
                         "Circuit breaker transitions by target state",
                         breaker=name, to=state)


def report_kube_write(outcome: str) -> None:
    """One guarded kube write by outcome: ok, retried_ok, failed,
    breaker_open (refused locally), budget_exhausted (retry budget
    empty)."""
    if outcome not in KUBE_WRITE_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_kube_writes_total",
                         "Guarded kube API writes by outcome",
                         outcome=outcome)


def report_template_quarantine(kind: str, quarantined: bool) -> None:
    """Device-path quarantine flag per template kind: 1 while the
    compiled program is benched (reviews serve from the interpreter), 0
    once the probe sweep succeeds and the device path is restored."""
    REGISTRY.gauge_set("gatekeeper_tpu_template_quarantined",
                       "1 while the template's device program is "
                       "quarantined after eval failures (interpreter "
                       "fallback serves its reviews)",
                       1 if quarantined else 0, kind=kind)


def report_mutation_request(admission_status: str, seconds: float) -> None:
    """One /v1/mutate decision (reference mutation stats reporter
    metric names)."""
    REGISTRY.counter_add("mutation_request_count",
                         "Count of mutation admission requests",
                         admission_status=admission_status)
    REGISTRY.observe("mutation_request_duration_seconds",
                     "Latency of mutation admission requests", seconds,
                     admission_status=admission_status)


def report_mutator_ingestion(status: str, seconds: float) -> None:
    if status not in INGESTION_STATUSES:
        status = LABEL_FOLD
    REGISTRY.counter_add("mutator_ingestion_count",
                         "Count of mutator ingestion actions by outcome",
                         status=status)
    REGISTRY.observe("mutator_ingestion_duration_seconds",
                     "Latency of mutator ingestion", seconds, status=status)


def report_mutators(counts: dict) -> None:
    """Cached-mutator gauges: per-kind counts plus the schema-conflict
    quarantine size ({"Assign": n, ..., "conflicting": n})."""
    for key, count in counts.items():
        if key == "conflicting":
            REGISTRY.gauge_set("mutator_conflicting_count",
                               "Mutators quarantined by the schema "
                               "conflict detector", count)
        else:
            REGISTRY.gauge_set("mutators",
                               "Current number of cached mutators", count,
                               kind=key)


def report_constraints(action: str, count: int) -> None:
    REGISTRY.gauge_set("constraints", "Current number of known constraints",
                       count, enforcement_action=action)


def report_constraint_templates(status: str, count: int) -> None:
    if status not in INGESTION_STATUSES:
        status = LABEL_FOLD
    REGISTRY.gauge_set("constraint_templates",
                       "Number of observed constraint templates", count,
                       status=status)


def report_template_ingestion(status: str, seconds: float) -> None:
    if status not in INGESTION_STATUSES:
        status = LABEL_FOLD
    REGISTRY.observe("constraint_template_ingestion_duration_seconds",
                     "Latency of constraint template ingestion", seconds,
                     status=status)


def report_sync(status: str, kind: str, count: int) -> None:
    if status not in INGESTION_STATUSES:
        status = LABEL_FOLD
    REGISTRY.gauge_set("sync", "Total number of resources replicated into "
                       "OPA", count, status=status, kind=kind)


def report_sync_duration(seconds: float) -> None:
    REGISTRY.observe("sync_duration_seconds", "Latency of sync operation",
                     seconds)


def report_last_sync(ts: Optional[float] = None) -> None:
    REGISTRY.gauge_set("sync_last_run_time", "Timestamp of last sync",
                       ts if ts is not None else time.time())


def report_compile_fallback(kind: str, reason: str) -> None:
    """One template kind falling back to the interpreter at ingestion,
    labeled by the stable Uncompilable reason CODE (ir/compile.py
    REASON_CODES — a bounded set, never free prose). Operators read
    this next to /debug/templates' per-kind fallback detail to see WHY
    a kind audits at Python speed instead of the device path."""
    # gklint: allow(metrics) reason=reason is Uncompilable.code, folded to the bounded REASON_CODES set in ir/compile.py (unknown->internal)
    REGISTRY.counter_add("gatekeeper_tpu_compile_fallback_total",
                         "Template kinds that fell back to the "
                         "interpreter at ingestion, by Uncompilable "
                         "reason code", kind=kind, reason=reason)


def report_device_demotion(kind: str, reason: str) -> None:
    if reason not in DEMOTION_REASONS:
        reason = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_device_demotions_total",
                         "Templates demoted from the device path to the "
                         "interpreter (a ~10^4x per-eval slowdown; should "
                         "stay 0 in steady state)", kind=kind, reason=reason)


def report_device_programs(warm: int, compiling: int) -> None:
    REGISTRY.gauge_set("gatekeeper_tpu_device_programs_warm",
                       "Device sweep programs whose XLA compilation has "
                       "completed (audits at these shapes run on the "
                       "device)", warm)
    REGISTRY.gauge_set("gatekeeper_tpu_device_programs_compiling",
                       "Device sweep programs currently compiling in the "
                       "background (audits serve from the host meanwhile)",
                       compiling)


def report_compile(source: str, outcome: str, seconds: float) -> None:
    """One device-program acquisition: source "aot" (deserialized from
    the AOT program store), "cache" (lower+compile answered by the
    persistent XLA cache), or "fresh" (cold XLA compile)."""
    if outcome not in COMPILE_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_compile_total",
                         "Device program acquisitions by source "
                         "(aot=deserialized executable, cache=persistent-"
                         "XLA-cache compile, fresh=cold compile) and "
                         "outcome", source=source, outcome=outcome)
    if outcome == "ok":
        REGISTRY.observe("gatekeeper_tpu_compile_seconds",
                         "Seconds spent acquiring device programs "
                         "(AOT deserialize or lower+compile)", seconds,
                         source=source)


def report_compile_cache(enabled: bool) -> None:
    REGISTRY.gauge_set("gatekeeper_tpu_compile_cache_enabled",
                       "1 when the persistent XLA compilation cache is "
                       "active; 0 means every restart recompiles (check "
                       "the cache dir volume/permissions)",
                       1.0 if enabled else 0.0)


def report_aot_store(enabled: bool, programs: int = 0) -> None:
    REGISTRY.gauge_set("gatekeeper_tpu_aot_store_enabled",
                       "1 when the AOT serialized-program store is "
                       "active (warm boots deserialize device programs "
                       "instead of recompiling)", 1.0 if enabled else 0.0)
    REGISTRY.gauge_set("gatekeeper_tpu_aot_store_programs",
                       "Serialized device programs in the AOT store",
                       programs)


def report_audit_sweep(path: str) -> None:
    """One audit sweep took `path`: "incremental" (delta-applied encoded
    inventory), "full_resync" (the periodic from-scratch re-encode
    backstop), or "full" (discovery / cache sweep without delta
    tracking)."""
    if path not in AUDIT_SWEEP_PATHS:
        path = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_audit_sweeps_total",
                         "Audit sweeps by evaluation path", path=path)


def report_materialize_pairs(path: str, n: int) -> None:
    """Firing (review, constraint) pairs the audit materialized, by
    message path: "vectorized" (numpy plan assembly, ir/vecmat.py),
    "exact" (per-pair evaluator — plan-less kinds and vetoed pairs),
    "capped" (past the per-constraint status cap: counted, message
    skipped)."""
    if path not in MATERIALIZE_PATHS:
        path = LABEL_FOLD
    if n > 0:
        REGISTRY.counter_add("gatekeeper_tpu_audit_materialize_pairs_total",
                             "Materialized firing pairs by message path",
                             float(n), path=path)


def report_msg_template_cache(outcome: str) -> None:
    """Message-plan cache lookup for one materialize batch: "hit" (plan
    reused), "miss" (plan compiled from the template head this call)."""
    if outcome not in CACHE_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_audit_msg_template_cache_total",
                         "Message-template plan cache lookups",
                         outcome=outcome)


def report_audit_dirty(dirty: int, total: int, vocab_grown: int = 0) -> None:
    """Incremental audit delta stats: dirty-set size, tracked inventory
    size, encoded-row cache hit ratio, and vocab growth this sweep."""
    REGISTRY.gauge_set("gatekeeper_tpu_audit_dirty_objects",
                       "Objects re-encoded by the last incremental audit "
                       "sweep (adds + updates + deletes applied)", dirty)
    REGISTRY.gauge_set("gatekeeper_tpu_audit_inventory_objects",
                       "Objects in the audit's tracked encoded inventory",
                       total)
    REGISTRY.gauge_set("gatekeeper_tpu_audit_cache_hit_ratio",
                       "Fraction of the inventory served from the encoded "
                       "cache by the last incremental sweep",
                       1.0 if total <= 0 else max(0.0, 1.0 - dirty / total))
    REGISTRY.gauge_set("gatekeeper_tpu_intern_strings_added",
                       "Strings interned during the last audit sweep "
                       "(vocabulary growth from churned label values)",
                       vocab_grown)


# violation detection latency: watch-event receipt -> the status write
# (or no-op confirmation) that published the verdict. Sub-second when
# the streaming audit is on; ~audit-interval/2 + sweep time without it.
DETECTION_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0, 60.0, 300.0)


def report_violation_detection(seconds: float, n: int = 1) -> None:
    """One (or n batched) watch events fully detected: received,
    evaluated against the encoded inventory, and reflected in
    constraint status — the streaming audit's headline latency."""
    for _ in range(n):
        REGISTRY.observe("gatekeeper_tpu_violation_detection_seconds",
                         "Latency from watch-event receipt to the "
                         "constraint-status write reflecting it "
                         "(event -> status)", seconds,
                         buckets=DETECTION_BUCKETS)


def report_stream_flush(outcome: str, n: int = 1) -> None:
    """One streaming-audit flush by outcome: ok (evaluated + statuses
    current), error (evaluation or write failed; the interval backstop
    repairs), or skipped (follower replica drained without writing)."""
    if outcome not in STREAM_FLUSH_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_stream_flushes_total",
                         "Streaming-audit dirty-row flushes by outcome",
                         n, outcome=outcome)


def report_backstop_drift(writes: int) -> None:
    """Status writes the interval reconciliation sweep had to issue
    while the streaming path was supposed to keep statuses current —
    each one is drift the event pipeline missed (or an external
    clobber it repaired). Should stay 0 in steady state."""
    if writes > 0:
        REGISTRY.counter_add("gatekeeper_tpu_audit_backstop_drift_total",
                             "Constraint-status drift repaired by the "
                             "interval reconciliation sweep while "
                             "streaming detection was active", writes)


def report_preview(outcome: str, seconds: float) -> None:
    """One what-if preview evaluation (candidate template/constraint
    swept against the full encoded inventory) by outcome."""
    if outcome not in PREVIEW_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_preview_requests_total",
                         "What-if preview evaluations by outcome",
                         outcome=outcome)
    if outcome == "ok":
        REGISTRY.observe("gatekeeper_tpu_preview_duration_seconds",
                         "Wall clock of one what-if preview sweep over "
                         "the cached inventory", seconds,
                         buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                                  10.0, 30.0, 60.0, 300.0))


def report_audit_status_writes(written: int, skipped: int) -> None:
    """Constraint-status write deltas: PATCHes issued vs skipped because
    the constraint's violation set was unchanged since the last write."""
    REGISTRY.counter_add("gatekeeper_tpu_audit_status_writes_total",
                         "Constraint status updates by outcome",
                         written, result="written")
    REGISTRY.counter_add("gatekeeper_tpu_audit_status_writes_total",
                         "Constraint status updates by outcome",
                         skipped, result="skipped")


def report_snapshot(op: str, outcome: str) -> None:
    """One state-snapshot save or restore by outcome. Save outcomes:
    ok | error (previous snapshot kept). Restore outcomes: ok | missing
    (no snapshot; plain cold start) | fallback (snapshot present but
    corrupt/stale/unusable — the pod proceeds down the cold path, never
    crash-loops)."""
    if outcome not in SNAPSHOT_OUTCOMES:
        outcome = LABEL_FOLD
    name = ("gatekeeper_tpu_snapshot_save_total" if op == "save"
            else "gatekeeper_tpu_snapshot_restore_total")
    REGISTRY.counter_add(name, f"State snapshot {op}s by outcome",
                         outcome=outcome)


def report_snapshot_age(seconds: float) -> None:
    REGISTRY.gauge_set("gatekeeper_tpu_snapshot_age_seconds",
                       "Age of the newest durable state snapshot (0 "
                       "right after a save; restart data-loss window is "
                       "bounded by this)", seconds)


def report_leader(is_leader: bool) -> None:
    """Leader-election state: both label series are kept so a flip is a
    visible edge on each (alerting on sum(gatekeeper_tpu_leader{
    is_leader=\"true\"}) != 1 catches split/no leader)."""
    REGISTRY.gauge_set("gatekeeper_tpu_leader",
                       "1 when this replica holds the leader lease "
                       "(audit sweep + status writers run here)",
                       1 if is_leader else 0, is_leader="true")
    REGISTRY.gauge_set("gatekeeper_tpu_leader",
                       "1 when this replica holds the leader lease "
                       "(audit sweep + status writers run here)",
                       0 if is_leader else 1, is_leader="false")


# per-stage latency bounds: admission stages live in the sub-ms to
# seconds range, audit phases run to minutes — one bound set covers
# both planes (the metric is shared, and bucket bounds are frozen at
# first registration)
STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                 30.0, 60.0, 120.0)

_STAGE_HELP = ("Latency of one named pipeline stage of a SAMPLED "
               "request (admission plane) or sweep phase (audit "
               "plane), from the request-scoped trace layer")


def _stage_engine(plane: str, engine) -> str:
    """Admission-plane stages carry the recording engine process's id
    (multi-engine planes decompose per chip); audit-plane phases are
    plane-global (their per-shard twin is the audit_shard histogram)."""
    if engine is not None:
        return str(engine)
    return _ENGINE_ID if plane == "admission" else ""


def report_stage(plane: str, stage: str, seconds: float,
                 engine: Optional[str] = None,
                 trace_id: Optional[str] = None) -> None:
    """One span of a sampled trace: the per-stage latency histogram
    that decomposes an admission p99 (or an audit sweep duration) into
    its pipeline stages. `trace_id` attaches as the bucket's
    OpenMetrics exemplar, so a slow bucket resolves to its
    /debug/traces flight-recorder entry."""
    REGISTRY.observe("gatekeeper_tpu_stage_duration_seconds",
                     _STAGE_HELP, seconds, buckets=STAGE_BUCKETS,
                     exemplar=trace_id,
                     plane=plane, stage=stage,
                     engine=_stage_engine(plane, engine))


def report_stage_bucketed(plane: str, stage: str, bucket_counts: list,
                          sum_: float, count: int,
                          engine: Optional[str] = None) -> None:
    """Merge a frontend's pre-aggregated stage-histogram delta (the
    frontends time their own stages — frontend_parse, the backplane
    forward — and ship them over the S frame like the forward-latency
    histogram; replaying spans one at a time over the wire would cost
    more than the stages being measured)."""
    REGISTRY.observe_bucketed("gatekeeper_tpu_stage_duration_seconds",
                              _STAGE_HELP, STAGE_BUCKETS, bucket_counts,
                              sum_, count, plane=plane, stage=stage,
                              engine=_stage_engine(plane, engine))


def report_scan_manifests(outcome: str, n: int = 1) -> None:
    """Fleet-scan manifest accounting by outcome: allow/deny verdicts,
    error records (malformed or unevaluated manifests — never silently
    dropped), dedup rejoins (verdict served by content hash without a
    wire trip), and skipped non-k8s documents."""
    if outcome not in SCAN_OUTCOMES:
        outcome = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_scan_manifests_total",
                         "Fleet-scan manifests by outcome",
                         n, outcome=outcome)


def report_scan_batch(tier: str, seconds: float) -> None:
    """One fleet-scan bulk batch round trip (begin to verdict receipt)
    on one wire tier — the feed-side read of whether the loader
    pipeline keeps the engine saturated (compare against the engine's
    own evaluate stage)."""
    if tier not in SCAN_TIERS:
        tier = LABEL_FOLD
    REGISTRY.counter_add("gatekeeper_tpu_scan_batches_total",
                         "Fleet-scan bulk batches completed per wire "
                         "tier", tier=tier)
    REGISTRY.observe("gatekeeper_tpu_scan_batch_duration_seconds",
                     "Fleet-scan bulk batch round-trip latency",
                     seconds, buckets=STAGE_BUCKETS, tier=tier)


def report_audit_shard(stage: str, shard: int, seconds: float) -> None:
    """Per-SHARD audit stage timing from the mesh slab loop: how one
    data shard's violation materialization (or its share of the sweep)
    costs, so a skewed shard — one device's slab carrying all the
    violating rows — is visible instead of averaged away."""
    REGISTRY.observe("gatekeeper_tpu_audit_shard_duration_seconds",
                     "Per-data-shard latency of one audit pipeline "
                     "stage in the mesh slab loop",
                     seconds, buckets=STAGE_BUCKETS,
                     stage=stage, shard=str(shard))


def report_audit_shard_fleet(configured: int, alive: int) -> None:
    """The sharded inventory plane's process census (AuditShard-
    Supervisor): configured vs currently-alive shard children. A
    sustained gap means a shard is crash-looping and its partition is
    being re-swept by the leader every round."""
    REGISTRY.gauge_set("gatekeeper_tpu_audit_shard_processes",
                       "Configured audit shard processes",
                       configured, state="configured")
    REGISTRY.gauge_set("gatekeeper_tpu_audit_shard_processes",
                       "Configured audit shard processes",
                       alive, state="alive")


def report_audit_shard_ownership(shard: int, objects: int) -> None:
    """Objects currently owned by one audit shard's inventory slice.
    Watch the SKEW, not the level: consistent hashing balances
    (GVK, namespace) partitions, so one towering shard means one hot
    namespace or a cluster-scoped kind pinning its whole population."""
    REGISTRY.gauge_set("gatekeeper_tpu_audit_shard_owned_objects",
                       "Inventory objects owned per audit shard",
                       objects, shard=str(shard))


def report_audit_shard_map(version: int, shards: int) -> None:
    """The leader's current shard-map assignment epoch. Bumps on every
    (re)build of the consistent-hash ring — a map that keeps bumping
    is a plane that keeps resizing."""
    REGISTRY.gauge_set("gatekeeper_tpu_audit_shard_map_version",
                       "Audit shard map assignment epoch", version)
    REGISTRY.gauge_set("gatekeeper_tpu_audit_shard_map_shards",
                       "Audit shard count in the active map", shards)


def report_audit_shard_rebalanced(moved: int) -> None:
    REGISTRY.counter_add("gatekeeper_tpu_audit_shard_rebalanced_total",
                         "Partitions moved between audit shards by "
                         "shard-map rebalances", float(moved))


def report_audit_shard_resync(shard: int) -> None:
    REGISTRY.counter_add("gatekeeper_tpu_audit_shard_resyncs_total",
                         "Full slice resyncs per audit shard (respawn "
                         "heals and replication-failure repairs)",
                         shard=str(shard))


def report_audit_shard_sweep(shard: int, seconds: float,
                             reviews: int) -> None:
    """One shard PROCESS's slice sweep (distinct from the mesh slab
    histogram above, which times device shards within one process)."""
    REGISTRY.observe("gatekeeper_tpu_audit_shard_sweep_seconds",
                     "Wall time of one audit shard process's slice "
                     "sweep", seconds, buckets=STAGE_BUCKETS,
                     shard=str(shard))
    REGISTRY.gauge_set("gatekeeper_tpu_audit_shard_swept_reviews",
                       "Reviews evaluated in the last slice sweep per "
                       "audit shard", reviews, shard=str(shard))


def report_trace(plane: str) -> None:
    REGISTRY.counter_add("gatekeeper_tpu_traces_total",
                         "Sampled traces completed per plane",
                         plane=plane)


def report_watch_manager(gvk_count: int, intended: int) -> None:
    REGISTRY.gauge_set("watch_manager_watched_gvk",
                       "Total number of watched GroupVersionKinds",
                       gvk_count)
    REGISTRY.gauge_set("watch_manager_intended_watch_gvk",
                       "Total number of GroupVersionKinds the watch manager "
                       "intends to watch", intended)
