"""Webhook certificate rotation.

Counterpart of the reference pkg/webhook/certs.go: self-signed CA +
server certificate (10-year validity, 90-day renewal lookahead, 12-hour
check interval, certs.go:35-41,332), persisted in the
gatekeeper-webhook-server-cert Secret, with the CA bundle injected into
the ValidatingWebhookConfiguration (certs.go:170) and re-injected when the
VWH or Secret changes (ReconcileVWH, certs.go:454).
"""

from __future__ import annotations

import base64
import datetime
import os
import threading
from typing import Optional

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID
except ImportError:  # gated: rotation raises, the rest of the control
    x509 = hashes = serialization = rsa = NameOID = None  # plane runs

from .kube import KubeError, NotFound
from .logging import logger


def _require_crypto() -> None:
    if x509 is None:
        raise RuntimeError(
            "cert rotation requires the 'cryptography' package; install "
            "it or run with --disable-cert-rotation")

log = logger("cert-rotation")

CA_VALIDITY = datetime.timedelta(days=10 * 365)
LOOKAHEAD = datetime.timedelta(days=90)
CHECK_INTERVAL = 12 * 3600  # seconds
SECRET_NAME = "gatekeeper-webhook-server-cert"
VWH_NAME = "gatekeeper-validating-webhook-configuration"
MWH_NAME = "gatekeeper-mutating-webhook-configuration"
SECRET_GVK = ("", "v1", "Secret")
VWH_GVK = ("admissionregistration.k8s.io", "v1beta1",
           "ValidatingWebhookConfiguration")
MWH_GVK = ("admissionregistration.k8s.io", "v1beta1",
           "MutatingWebhookConfiguration")


def _new_key():
    _require_crypto()
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())


def _pem_cert(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def generate_ca(common_name: str = "gatekeeper-ca"):
    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + CA_VALIDITY)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False), critical=True)
            .sign(key, hashes.SHA256()))
    return key, cert


def generate_server_cert(ca_key, ca_cert, dns_names: list[str]):
    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(x509.Name([
                x509.NameAttribute(NameOID.COMMON_NAME, dns_names[0])]))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + CA_VALIDITY)
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName(n) for n in dns_names]), critical=False)
            .add_extension(x509.ExtendedKeyUsage(
                [x509.ExtendedKeyUsageOID.SERVER_AUTH]), critical=False)
            .sign(ca_key, hashes.SHA256()))
    return key, cert


def _needs_refresh(cert_pem: bytes) -> bool:
    _require_crypto()
    try:
        cert = x509.load_pem_x509_certificate(cert_pem)
    except ValueError:
        return True
    now = datetime.datetime.now(datetime.timezone.utc)
    return cert.not_valid_after_utc - now < LOOKAHEAD


class CertRotator:
    def __init__(self, kube, cert_dir: str,
                 service_name: str = "gatekeeper-webhook-service",
                 namespace: str = "gatekeeper-system",
                 secret_name: str = SECRET_NAME,
                 vwh_name: str = VWH_NAME,
                 mwh_name: str = MWH_NAME):
        self.kube = kube
        self.cert_dir = cert_dir
        self.dns_names = [
            f"{service_name}.{namespace}.svc",
            f"{service_name}.{namespace}.svc.cluster.local",
        ]
        self.namespace = namespace
        self.secret_name = secret_name
        self.vwh_name = vwh_name
        self.mwh_name = mwh_name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reconcile_thread: Optional[threading.Thread] = None
        self._registrar = None
        self._ca_pem: Optional[bytes] = None
        # the 12h rotation loop and the watch-driven reconciler both
        # call refresh_certs; interleaved regeneration would mix cert
        # and key from different generations on disk
        self._refresh_lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    def start(self, watch_manager=None) -> None:
        self.refresh_certs()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cert-rotator")
        self._thread.start()
        if watch_manager is not None:
            self.start_reconciler(watch_manager)

    def stop(self) -> None:
        self._stop.set()
        if self._registrar is not None:
            from .kube import WatchEvent

            # unblock the reconcile drain, then drop the watches
            self._registrar.events.put(WatchEvent("_STOP", {}))
            for gvk in list(self._registrar.gvks):
                self._registrar.remove_watch(gvk)

    def _loop(self) -> None:
        while not self._stop.wait(CHECK_INTERVAL):
            try:
                self.refresh_certs()
            except Exception as e:
                log.error("cert refresh failed", details=str(e))

    # ----------------------------------------------------------- reconciler

    def start_reconciler(self, watch_manager) -> None:
        """ReconcileVWH analog (reference certs.go:454-530): watch the
        ValidatingWebhookConfiguration and the cert Secret and re-inject
        the CA bundle the moment either changes — a VWH recreated
        between 12-hour refresh ticks must not serve an unbundled config
        until the next tick."""
        reg = watch_manager.registrar("cert-reconciler")
        reg.add_watch(VWH_GVK)
        reg.add_watch(MWH_GVK)
        reg.add_watch(SECRET_GVK)
        self._registrar = reg
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True,
            name="cert-reconciler")
        self._reconcile_thread.start()

    def _reconcile_loop(self) -> None:
        import queue

        reg = self._registrar
        while not self._stop.is_set():
            try:
                event = reg.events.get(timeout=1.0)
            except queue.Empty:
                continue
            try:
                self._reconcile_event(event)
            except Exception as e:
                log.error("cert reconcile failed", details=str(e))

    def _reconcile_event(self, event) -> None:
        obj = event.object or {}
        meta = obj.get("metadata") or {}
        kind = obj.get("kind")
        if kind in ("ValidatingWebhookConfiguration",
                    "MutatingWebhookConfiguration"):
            if meta.get("name") not in (self.vwh_name, self.mwh_name) or \
                    event.type == "DELETED":
                return
            if self._ca_pem:
                self.inject_ca(self._ca_pem)
        elif kind == "Secret":
            if meta.get("name") != self.secret_name or \
                    (meta.get("namespace") or "") != self.namespace:
                return
            # deleted or externally modified: regenerate/reload and
            # re-inject (refresh is idempotent when the secret is valid,
            # so our own writes do not loop)
            self.refresh_certs()

    # -------------------------------------------------------------- refresh

    def refresh_certs(self) -> None:
        with self._refresh_lock:
            self._refresh_certs_locked()

    def _refresh_certs_locked(self) -> None:
        secret = self._load_secret()
        data = (secret or {}).get("data") or {}
        ca_pem = base64.b64decode(data.get("ca.crt") or b"")
        cert_pem = base64.b64decode(data.get("tls.crt") or b"")
        if not ca_pem or not cert_pem or _needs_refresh(cert_pem) or \
                _needs_refresh(ca_pem):
            log.info("generating new webhook certificates")
            ca_key, ca_cert = generate_ca()
            key, cert = generate_server_cert(ca_key, ca_cert, self.dns_names)
            ca_pem = _pem_cert(ca_cert)
            cert_pem = _pem_cert(cert)
            key_pem = _pem_key(key)
            self._store_secret(ca_pem, _pem_key(ca_key), cert_pem, key_pem)
        else:
            key_pem = base64.b64decode(data.get("tls.key") or b"")
        self._write_files(cert_pem, key_pem, ca_pem)
        self._ca_pem = ca_pem
        self.inject_ca(ca_pem)

    def _load_secret(self) -> Optional[dict]:
        try:
            return self.kube.get(SECRET_GVK, self.secret_name,
                                 self.namespace)
        except (NotFound, KubeError):
            return None

    def _store_secret(self, ca_pem, ca_key_pem, cert_pem, key_pem) -> None:
        secret = {
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": self.secret_name,
                         "namespace": self.namespace},
            "type": "kubernetes.io/tls",
            "data": {
                "ca.crt": base64.b64encode(ca_pem).decode(),
                "ca.key": base64.b64encode(ca_key_pem).decode(),
                "tls.crt": base64.b64encode(cert_pem).decode(),
                "tls.key": base64.b64encode(key_pem).decode(),
            },
        }
        try:
            self.kube.apply(secret)
        except KubeError as e:
            log.warning("could not persist cert secret", details=str(e))

    def _write_files(self, cert_pem: bytes, key_pem: bytes,
                     ca_pem: bytes) -> None:
        os.makedirs(self.cert_dir, exist_ok=True)
        for fname, blob in (("tls.crt", cert_pem), ("tls.key", key_pem),
                            ("ca.crt", ca_pem)):
            path = os.path.join(self.cert_dir, fname)
            with open(path, "wb") as f:
                f.write(blob)
        os.chmod(os.path.join(self.cert_dir, "tls.key"), 0o600)

    def inject_ca(self, ca_pem: bytes) -> None:
        """caBundle injection into every webhook of the VWH and (when
        deployed) the MutatingWebhookConfiguration (certs.go:170-233)."""
        bundle = base64.b64encode(ca_pem).decode()
        for gvk, name in ((VWH_GVK, self.vwh_name),
                          (MWH_GVK, self.mwh_name)):
            try:
                cfg = self.kube.get(gvk, name)
            except (NotFound, KubeError):
                continue
            changed = False
            for wh in cfg.get("webhooks") or []:
                cc = wh.setdefault("clientConfig", {})
                if cc.get("caBundle") != bundle:
                    cc["caBundle"] = bundle
                    changed = True
            if changed:
                try:
                    self.kube.update(cfg)
                    log.info("injected CA bundle into webhook "
                             "configuration", details={"name": name})
                except KubeError as e:
                    log.warning("CA injection failed", details=str(e))
