"""Dynamic watch manager.

Counterpart of the reference pkg/watch + the dynamiccache fork: ref-counted
per-GVK watches shared by registrars (manager.go:139-224), a central
fan-out delivering events to every interested registrar's queue
(manager.go:287-349), replay of already-cached objects to late-joining
registrars (replay.go), and removable watches — the whole reason the
reference forks controller-runtime's cache (GetInformerNonBlocking +
Remove) is to tear informers down dynamically, which here is just
cancelling a subscription.
"""

from __future__ import annotations

import copy
import queue
import threading
from typing import Callable, Optional

from .kube import GVK, WatchEvent


class WatchError(Exception):
    pass


class Registrar:
    """A controller's handle on the watch manager. Events for any GVK the
    registrar watches land in its queue as (event_type, object)."""

    def __init__(self, name: str, manager: "WatchManager"):
        self.name = name
        self.manager = manager
        self.events: "queue.Queue[WatchEvent]" = queue.Queue()
        self.gvks: set[GVK] = set()

    def add_watch(self, gvk: GVK) -> None:
        self.manager._add_watch(self, tuple(gvk))

    def remove_watch(self, gvk: GVK) -> None:
        self.manager._remove_watch(self, tuple(gvk))

    def replace_watches(self, gvks: list[GVK]) -> None:
        """Atomically swap the watched set (reference registrar
        ReplaceWatch, used by the config controller)."""
        want = {tuple(g) for g in gvks}
        for g in list(self.gvks - want):
            self.remove_watch(g)
        for g in sorted(want - self.gvks):
            self.add_watch(g)


class _WatchRecord:
    def __init__(self):
        self.registrars: set[Registrar] = set()
        self.cancel: Optional[Callable[[], None]] = None
        self.cache: dict[tuple, dict] = {}  # (ns, name) -> obj


class WatchManager:
    """Ref-counted dynamic watches over a KubeClient."""

    def __init__(self, kube):
        self.kube = kube
        self._lock = threading.RLock()
        self._records: dict[GVK, _WatchRecord] = {}
        self.paused = False

    # ----------------------------------------------------------- intents

    def watched_gvks(self) -> list[GVK]:
        with self._lock:
            return sorted(self._records)

    def is_watched(self, gvk: GVK) -> bool:
        with self._lock:
            return tuple(gvk) in self._records

    def _add_watch(self, registrar: Registrar, gvk: GVK) -> None:
        with self._lock:
            rec = self._records.get(gvk)
            if rec is None:
                rec = _WatchRecord()
                self._records[gvk] = rec
                # subscribe BEFORE opening the kube watch: the initial
                # list-events fan out synchronously and must reach this
                # first registrar, not just the cache
                rec.registrars.add(registrar)
                registrar.gvks.add(gvk)

                def deliver(event: WatchEvent, _gvk=gvk, _rec=rec):
                    self._fanout(_gvk, _rec, event)

                rec.cancel = self.kube.watch(gvk, deliver, send_initial=True)
            elif registrar not in rec.registrars:
                # replay the cache so late joiners see existing objects
                # (reference watch/replay.go:136-183)
                for obj in sorted(rec.cache.values(),
                                  key=lambda o: _okey(o)):
                    registrar.events.put(WatchEvent("ADDED", obj))
            rec.registrars.add(registrar)
            registrar.gvks.add(gvk)

    def _remove_watch(self, registrar: Registrar, gvk: GVK) -> None:
        with self._lock:
            rec = self._records.get(gvk)
            registrar.gvks.discard(gvk)
            if rec is None:
                return
            rec.registrars.discard(registrar)
            if not rec.registrars:
                if rec.cancel:
                    rec.cancel()
                del self._records[gvk]

    def _fanout(self, gvk: GVK, rec: _WatchRecord, event: WatchEvent) -> None:
        with self._lock:
            key = _okey(event.object)
            if event.type == "DELETED":
                rec.cache.pop(key, None)
            else:
                rec.cache[key] = event.object
            if self.paused:
                return
            targets = list(rec.registrars)
        for r in targets:
            r.events.put(event)

    def cached_get(self, gvk: GVK, name: str,
                   namespace: str = "") -> Optional[dict]:
        """Latest cached object for (gvk, ns, name) — the informer-cache
        read the reference reconcilers use. None when the object is gone
        from the cache (at least as new as any drained event; _fanout pops
        it on DELETED) or the GVK is no longer watched."""
        with self._lock:
            rec = self._records.get(tuple(gvk))
            if rec is None:
                return None
            obj = rec.cache.get((namespace or "", name))
            # deep copy: callers mutate (status writes) and the cache entry
            # is shared with late-joiner replay and other registrars
            return copy.deepcopy(obj) if obj is not None else None

    def cached_objects(self, gvk: GVK) -> list[dict]:
        with self._lock:
            rec = self._records.get(tuple(gvk))
            if rec is None:
                return []
            return [rec.cache[k] for k in sorted(rec.cache)]

    def registrar(self, name: str) -> Registrar:
        return Registrar(name, self)

    def stop(self) -> None:
        with self._lock:
            for rec in self._records.values():
                if rec.cancel:
                    rec.cancel()
            self._records.clear()


def _okey(obj: dict) -> tuple:
    meta = obj.get("metadata") or {}
    return (meta.get("namespace") or "", meta.get("name") or "")
