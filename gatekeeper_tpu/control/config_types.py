"""Config CRD helpers (config.gatekeeper.sh/v1alpha1).

Counterpart of the reference api/v1alpha1/config_types.go:22-92:
spec.sync.syncOnly lists the GVKs replicated into the driver inventory;
spec.validation.traces opts (user, kind) pairs into evaluation tracing.
"""

from __future__ import annotations

from typing import Optional

CONFIG_API_VERSION = "config.gatekeeper.sh/v1alpha1"


def config_crd() -> dict:
    """The Config CustomResourceDefinition manifest."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1beta1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "configs.config.gatekeeper.sh"},
        "spec": {
            "group": "config.gatekeeper.sh",
            "names": {"kind": "Config", "listKind": "ConfigList",
                      "plural": "configs", "singular": "config"},
            "scope": "Namespaced",
            "version": "v1alpha1",
            "versions": [{"name": "v1alpha1", "served": True,
                          "storage": True}],
            "subresources": {"status": {}},
            "validation": {"openAPIV3Schema": {"properties": {"spec": {
                "properties": {
                    "sync": {"properties": {"syncOnly": {
                        "type": "array",
                        "items": {"properties": {
                            "group": {"type": "string"},
                            "version": {"type": "string"},
                            "kind": {"type": "string"}}}}}},
                    "validation": {"properties": {"traces": {
                        "type": "array",
                        "items": {"properties": {
                            "user": {"type": "string"},
                            "kind": {"properties": {
                                "group": {"type": "string"},
                                "version": {"type": "string"},
                                "kind": {"type": "string"}}},
                            "dump": {"type": "string"}}}}}},
                }}}}},
        },
    }


def trace_enabled(traces: list, username: Optional[str],
                  gvk: tuple) -> tuple[bool, bool]:
    """(trace?, dump?) for a request, per the Config CRD's traces
    (reference policy.go:290-309)."""
    group, version, kind = gvk
    for t in traces or []:
        if not isinstance(t, dict):
            continue
        if t.get("user") and t.get("user") != username:
            continue
        tk = t.get("kind") or {}
        if tk.get("group", group) not in ("", group) and tk.get("group") != group:
            continue
        if tk.get("kind") and tk.get("kind") != kind:
            continue
        return True, (t.get("dump") == "All")
    return False, False
