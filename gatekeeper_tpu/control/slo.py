"""Declarative SLOs compiled against the metrics Registry into
multi-window burn-rate gauges.

PR 6 gave the serving plane per-stage latency histograms and PR 13's
saturation gauges say WHERE capacity goes — this module says whether
the plane is meeting its promises, in the shape alerting actually
consumes: error-budget BURN RATES over two windows (5m/1h), the
multiwindow-multi-burn-rate pattern from the SRE workbook. Burn rate
1.0 means the objective is consuming its budget exactly at the
sustained-compliance rate; 14.4 over 5m (for a 99.9% objective over a
30-day budget) is the classic fast-burn page, 6 over 1h the slow-burn
ticket.

Objectives are DECLARATIVE — a name, a target, and which existing
Registry series to read:

  * `latency`: a histogram family + a threshold that must be one of
    its bucket bounds; "target fraction of observations complete
    under threshold". Good events = the cumulative bucket count at the
    threshold bound, total = _count — no new instrumentation, the
    compliance math rides the histograms the planes already emit.
  * `availability`: a counter family with a status label; bad label
    values are enumerated (shed/timeout/error for admission). Good =
    total - bad.

`SloEngine` samples each objective's (good, total) totals on a ring
(one sample per `sample_interval`, sized to cover the longest window),
computes per-window bad-event fractions from the deltas, divides by
the error budget (1 - target), and exports
`gatekeeper_tpu_slo_burn_rate{slo,window}` plus
`gatekeeper_tpu_slo_target{slo}`. `/debug/slo` dumps the full
compliance picture (targets, windows, burn rates, event counts, alert
reference thresholds) as JSON.

Windows shorter than the accumulated history use the oldest available
sample (a freshly booted pod reports burn over its own lifetime — the
honest answer, not zero).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from .logging import logger
from .metrics import REGISTRY, Registry

log = logger("slo")

# the two standard alerting windows: fast-burn (page) and slow-burn
# (ticket). Keys are the label values on the burn-rate gauge.
DEFAULT_WINDOWS: dict[str, float] = {"5m": 300.0, "1h": 3600.0}

# SRE-workbook reference burn thresholds for a 30-day budget, surfaced
# in /debug/slo so the operator wiring alerts doesn't re-derive them
ALERT_REFERENCE = {"5m": 14.4, "1h": 6.0}

DEFAULT_SAMPLE_INTERVAL_S = 15.0


class SloObjective:
    """One declarative objective bound to an existing metric family."""

    def __init__(self, name: str, kind: str, target: float, metric: str,
                 threshold_s: Optional[float] = None,
                 status_label: str = "",
                 bad_statuses: tuple = ()):
        if kind not in ("latency", "availability"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1): {target}")
        if kind == "latency" and (threshold_s is None or threshold_s <= 0):
            raise ValueError(f"latency SLO {name!r} needs threshold_s")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.metric = metric
        self.threshold_s = threshold_s
        self.status_label = status_label
        self.bad_statuses = frozenset(bad_statuses)
        self._bound_warned = False

    # ------------------------------------------------------------ totals

    def totals(self, registry: Registry) -> tuple[float, float]:
        """(good, total) event totals right now, summed across every
        label series of the bound family. Monotonic by construction
        (counters / histogram counts), which is what the windowed
        delta math assumes."""
        snap = registry.snapshot((self.metric,)).get(self.metric)
        if snap is None:
            return 0.0, 0.0
        if self.kind == "latency":
            return self._latency_totals(snap)
        return self._availability_totals(snap)

    def _latency_totals(self, snap: dict) -> tuple[float, float]:
        buckets = list(snap.get("buckets") or ())
        # the threshold bucket: smallest bound >= threshold, resolved
        # per sample (the family may not exist yet at construction).
        # A threshold past every finite bound degrades to "anything
        # not in +Inf overflow is good".
        k = len(buckets) - 1
        for i, b in enumerate(buckets):
            if self.threshold_s <= b + 1e-12:
                k = i
                break
        if buckets and not self._bound_warned and not any(
                abs(self.threshold_s - b) <= 1e-9 for b in buckets):
            # a threshold between bounds silently rounds UP to the next
            # bound (overcounting good events, under-reporting burn) —
            # every other bad --slo-* value fails loudly, this one must
            # at least SAY it is measuring a different promise
            self._bound_warned = True
            log.warning(
                "SLO latency threshold is not a histogram bucket bound;"
                " compliance is measured at the next bound up",
                details={"slo": self.name,
                         "threshold_s": self.threshold_s,
                         "effective_bound_s": buckets[k],
                         "bounds": list(buckets)})
        good = total = 0.0
        for _labels, counts, _sum, n in snap.get("hist") or []:
            total += n
            # +Inf overflow (counts[-1]) is bad by definition: it is
            # never inside the k-prefix sum
            good += sum(counts[: k + 1])
        return good, total

    def _availability_totals(self, snap: dict) -> tuple[float, float]:
        label_names = list(snap.get("labels") or ())
        try:
            idx = label_names.index(self.status_label)
        except ValueError:
            idx = 0 if label_names else -1
        good = total = 0.0
        for labelvals, v in snap.get("values") or []:
            total += v
            status = labelvals[idx] if 0 <= idx < len(labelvals) else ""
            if status not in self.bad_statuses:
                good += v
        return good, total

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind,
               "target": self.target, "metric": self.metric}
        if self.kind == "latency":
            out["threshold_s"] = self.threshold_s
        else:
            out["bad_statuses"] = sorted(self.bad_statuses)
        return out


def default_objectives(admission_p99_s: float = 0.1,
                       availability_target: float = 0.999,
                       detection_p99_s: float = 1.0) -> list[SloObjective]:
    """The shipped objective set (each tunable via --slo-* flags):

    * admission_p99_latency — 99% of admission decisions complete
      under `admission_p99_s` (reads request_duration_seconds);
    * availability — at most 1-target of admission requests end
      shed / timeout / error (reads request_count);
    * violation_detection_p99 — 99% of streaming-audit detections
      (event -> status write) complete under `detection_p99_s`.
    """
    return [
        SloObjective("admission_p99_latency", "latency", 0.99,
                     "request_duration_seconds",
                     threshold_s=admission_p99_s),
        SloObjective("availability", "availability",
                     availability_target, "request_count",
                     status_label="admission_status",
                     bad_statuses=("shed", "timeout", "error")),
        SloObjective("violation_detection_p99", "latency", 0.99,
                     "gatekeeper_tpu_violation_detection_seconds",
                     threshold_s=detection_p99_s),
    ]


class SloEngine:
    """Samples objective totals on a ring and exports burn rates.

    The ring holds (monotonic_ts, {slo: (good, total)}) samples at
    `sample_interval`, sized to span the longest window with slack; a
    window's burn rate is computed from the delta between now and the
    newest sample at least `window` old (or the oldest held — a young
    process reports burn over its lifetime)."""

    def __init__(self, objectives: list[SloObjective],
                 registry: Registry = REGISTRY,
                 windows: Optional[dict[str, float]] = None,
                 sample_interval_s: float = DEFAULT_SAMPLE_INTERVAL_S):
        self.objectives = list(objectives)
        self.registry = registry
        self.windows = dict(windows or DEFAULT_WINDOWS)
        self.sample_interval_s = max(1.0, float(sample_interval_s))
        keep = int(max(self.windows.values())
                   / self.sample_interval_s) + 8
        self._samples: deque = deque(maxlen=keep)
        self._last_rates: dict = {}  # latest export(), for latest()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- sampling

    def sample(self, now: Optional[float] = None) -> None:
        """Take one totals sample (the loop calls this; tests inject
        `now` to fabricate history without sleeping)."""
        totals = {}
        for obj in self.objectives:
            try:
                totals[obj.name] = obj.totals(self.registry)
            except Exception as e:  # a broken family must not kill SLOs
                log.warning("SLO totals read failed",
                            details={"slo": obj.name, "error": str(e)})
        with self._lock:
            self._samples.append(
                (now if now is not None else time.monotonic(), totals))

    def _window_anchor(self, now: float, window_s: float
                       ) -> Optional[tuple]:
        """Newest sample at least `window_s` old, else the oldest held."""
        with self._lock:
            if not self._samples:
                return None
            anchor = self._samples[0]
            for ts, totals in self._samples:
                if now - ts >= window_s:
                    anchor = (ts, totals)
                else:
                    break
            return anchor

    def burn_rates(self, now: Optional[float] = None) -> dict:
        """{slo: {window: {burn_rate, bad, total, window_actual_s}}}
        over the configured windows, from the ring's deltas."""
        now = now if now is not None else time.monotonic()
        cur = {}
        for obj in self.objectives:
            try:
                cur[obj.name] = obj.totals(self.registry)
            except Exception:
                continue
        out: dict = {}
        for wname, wsec in sorted(self.windows.items(),
                                  key=lambda kv: kv[1]):
            anchor = self._window_anchor(now, wsec)
            for obj in self.objectives:
                if obj.name not in cur:
                    continue
                good_now, total_now = cur[obj.name]
                if anchor is None:
                    good_then = total_then = 0.0
                    actual = 0.0
                else:
                    good_then, total_then = anchor[1].get(
                        obj.name, (0.0, 0.0))
                    actual = now - anchor[0]
                d_total = max(0.0, total_now - total_then)
                d_bad = max(0.0, (total_now - good_now)
                            - (total_then - good_then))
                budget = 1.0 - obj.target
                bad_frac = (d_bad / d_total) if d_total > 0 else 0.0
                burn = bad_frac / budget if budget > 0 else 0.0
                out.setdefault(obj.name, {})[wname] = {
                    "burn_rate": round(burn, 4),
                    "bad": d_bad, "total": d_total,
                    "window_actual_s": round(actual, 1),
                }
        return out

    # ----------------------------------------------------------- exports

    def latest(self) -> dict:
        """The most recent export()'s burn rates, without recomputing:
        the adaptive controller's per-tick signal read. Shape matches
        burn_rates(); {} before the first export. The reference is
        swapped atomically and never mutated after publication, so
        readers need no lock."""
        return self._last_rates

    def export(self, now: Optional[float] = None) -> dict:
        """Refresh the burn-rate gauges from the ring; returns what it
        exported (the /debug/slo payload core)."""
        rates = self.burn_rates(now)
        self._last_rates = rates
        for obj in self.objectives:
            self.registry.gauge_set(
                "gatekeeper_tpu_slo_target",
                "Declared SLO target (fraction of good events promised)",
                obj.target, slo=obj.name)
            for wname, ent in (rates.get(obj.name) or {}).items():
                self.registry.gauge_set(
                    "gatekeeper_tpu_slo_burn_rate",
                    "Error-budget burn rate per objective and window "
                    "(1.0 = consuming budget exactly at the sustained-"
                    "compliance rate; see /debug/slo for alert "
                    "reference thresholds)",
                    ent["burn_rate"], slo=obj.name, window=wname)
        return rates

    def status(self, now: Optional[float] = None) -> dict:
        """The /debug/slo payload: objectives, current burn rates per
        window, and the standard alerting reference."""
        rates = self.burn_rates(now)
        objectives = []
        for obj in self.objectives:
            try:
                good, total = obj.totals(self.registry)
            except Exception:
                good = total = 0.0
            compliance = (good / total) if total > 0 else None
            objectives.append({
                **obj.describe(),
                "events_total": total,
                "events_bad": total - good,
                "compliance": (round(compliance, 6)
                               if compliance is not None else None),
                "windows": rates.get(obj.name, {}),
            })
        return {
            "objectives": objectives,
            "windows_s": self.windows,
            "sample_interval_s": self.sample_interval_s,
            "samples_held": len(self._samples),
            "alert_reference_burn_rates": dict(ALERT_REFERENCE),
            "note": "burn_rate 1.0 consumes the error budget exactly "
                    "at the sustained-compliance rate; page on the 5m "
                    "fast-burn threshold, ticket on the 1h slow burn",
        }

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self.sample()  # seed the ring so the first export has an anchor
        self.export()
        self._thread = threading.Thread(target=self._loop, name="slo",
                                        daemon=True)
        self._thread.start()
        log.info("SLO engine started",
                 details={"objectives": [o.name for o in self.objectives],
                          "windows": self.windows})

    def _loop(self) -> None:
        while not self._stop.wait(self.sample_interval_s):
            try:
                self.sample()
                self.export()
            except Exception as e:  # the SLO layer must never crash a pod
                log.warning("SLO tick failed", details=str(e))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # wait the sampler out BEFORE zeroing: an in-flight tick's
            # export() would re-write the last burst's burn after the
            # zero (same discipline as AuditManager.stop)
            self._thread.join(timeout=10.0)
        # burn-rate gauges are SET-only and alert-bearing: a stopped
        # engine in a still-serving process (embedder, test harness)
        # must not export its last burst's burn — and keep a page
        # firing — forever
        for obj in self.objectives:
            for wname in self.windows:
                try:
                    self.registry.gauge_set(
                        "gatekeeper_tpu_slo_burn_rate",
                        "Error-budget burn rate per objective and "
                        "window", 0.0, slo=obj.name, window=wname)
                except Exception:
                    pass
